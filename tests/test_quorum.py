"""Quorum cluster plane: majority-vote promotion (VoteLeader campaigns, one
vote per epoch, stand-downs), quorum acks with the per-partition
high-watermark gating follower-served reads, checkpoint-codec partition
slices (FetchSlice/InstallSlice), live partition handoff, and the 3-broker
double-failure chaos schedules (3-seed fast variant in tier-1; the long soak
is ``slow``)."""

import json
import os
import threading
import time

import pytest

from conftest import free_ports
from surge_tpu.config import Config
from surge_tpu.log import (
    GrpcLogTransport,
    InMemoryLog,
    LogRecord,
    LogServer,
    TopicSpec,
)
from surge_tpu.log import log_service_pb2 as pb
from surge_tpu.store.checkpoint import (
    decode_partition_slice,
    encode_partition_slice,
)
from surge_tpu.testing.faults import FaultPlane, FaultRule

QUORUM_CFG = Config(overrides={
    "surge.log.replication-ack-timeout-ms": 1_500,
    "surge.log.replication-isr-timeout-ms": 600,
    "surge.log.failover.probe-interval-ms": 150,
    "surge.log.failover.probe-failures": 2,
    "surge.log.quorum.vote-timeout-ms": 600,
    "surge.log.quorum.vote-rounds": 6,
})


def rec(topic, key, value, partition=0, offset=0):
    return LogRecord(topic=topic, key=key, value=value, partition=partition,
                     offset=offset)


def _trio(config=QUORUM_CFG, auto_promote=True, extra=None):
    """3-broker cluster: one leader replicating to two followers, every
    broker holding the SAME full quorum-peer list (self included — dropped
    by address wherever the peer set is consulted)."""
    cfg = config
    if extra:
        cfg = Config(overrides={**config.overrides, **extra})
    ports = free_ports(3)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    followers = []
    for i in (1, 2):
        f = LogServer(InMemoryLog(), port=ports[i], follower_of=addrs[0],
                      auto_promote=auto_promote, config=cfg,
                      quorum_peers=addrs)
        f.start()
        followers.append(f)
    leader = LogServer(InMemoryLog(), port=ports[0],
                       replicate_to=[addrs[1], addrs[2]], config=cfg,
                       quorum_peers=addrs, auto_promote=auto_promote)
    leader.start()
    return leader, followers, addrs


def _stop_all(*servers):
    for s in servers:
        try:
            s.stop()
        except Exception:  # noqa: BLE001 — already killed
            pass


def _commit_n(client, txn_id, n, topic="ev", prefix="v", timeout=30.0):
    acked = []
    producer = None
    from surge_tpu.log.transport import NotLeaderError, ProducerFencedError

    for i in range(n):
        payload = f"{prefix}-{i}".encode()
        deadline = time.monotonic() + timeout
        while True:
            try:
                if producer is None:
                    producer = client.transactional_producer(txn_id)
                producer.begin()
                producer.send(rec(topic, f"k{i}", payload))
                producer.commit()
                break
            except (ProducerFencedError, NotLeaderError):
                producer = None
            except Exception:  # noqa: BLE001 — broker mid-failover
                if producer is not None and producer.in_transaction:
                    producer.abort()
                time.sleep(0.05)
            if time.monotonic() > deadline:
                raise TimeoutError(f"commit {i} never acked")
        acked.append(payload)
    return acked


def _assert_exactly_once(log, topic, acked, partitions=1):
    present = []
    for p in range(partitions):
        present.extend(r.value for r in log.read(topic, p))
    for payload in acked:
        n = present.count(payload)
        assert n == 1, f"acked payload {payload!r} appears {n} times"


def _wait_leader(servers, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [s for s in servers if s.role == "leader" and not s._dead]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise TimeoutError("no (single) leader emerged")


# -- partition slice codec ------------------------------------------------------------


def test_partition_slice_roundtrip_with_compaction_holes():
    records = [rec("ev", f"k{o}", f"v{o}".encode(), offset=o)
               for o in (0, 1, 2, 5, 6, 9)]  # holes at 3-4, 7-8 (compaction)
    data = encode_partition_slice(records, "ev", 0)
    header, out = decode_partition_slice(data)
    assert header["topic"] == "ev" and header["count"] == 6
    assert header["blocks"] == 3  # one block per contiguous-offset run
    assert [(r.offset, r.key, r.value) for r in out] == \
        [(r.offset, r.key, r.value) for r in records]


def test_partition_slice_rejects_truncation_and_garbage():
    records = [rec("ev", f"k{o}", b"x" * 50, offset=o) for o in range(20)]
    data = encode_partition_slice(records, "ev", 0)
    with pytest.raises(Exception):
        decode_partition_slice(data[:-30])  # torn tail
    with pytest.raises(ValueError):
        decode_partition_slice(b"JUNK" + data[4:])  # bad magic


# -- vote semantics -------------------------------------------------------------------


def _vote_req(candidate, leader, epoch):
    return pb.TxnRequest(op="vote", txn_seq=epoch, records=[pb.RecordMsg(
        has_value=True, value=json.dumps(
            {"candidate": candidate, "leader": leader}).encode())])


def _verdict(reply):
    assert reply.ok
    return json.loads(reply.records[0].value)


def test_vote_denied_while_leader_alive_then_granted_after_death():
    leader, (f1, f2), addrs = _trio(auto_promote=False)
    try:
        # a live LEADER never grants: it is the proof the candidate is wrong
        v = _verdict(leader.VoteLeader(_vote_req(addrs[1], addrs[0], 5), None))
        assert not v["granted"] and v["reason"] == "voter-is-leader"
        assert v["leader_alive"]
        # a follower that can still REACH the leader denies too
        v = _verdict(f2.VoteLeader(_vote_req(addrs[1], addrs[0], 5), None))
        assert not v["granted"] and v["reason"] == "leader-alive"
        leader.kill()
        if leader.kill_done is not None:
            leader.kill_done.wait(10)
        # leader unreachable from the voter's vantage too: granted
        v = _verdict(f2.VoteLeader(_vote_req(addrs[1], addrs[0], 6), None))
        assert v["granted"]
        # one vote per epoch: a SECOND candidate at the same epoch is denied
        v = _verdict(f2.VoteLeader(_vote_req(addrs[2], addrs[0], 6), None))
        assert not v["granted"] and v["reason"] == "already-voted"
        # the SAME candidate re-asking its epoch is re-granted (idempotent)
        v = _verdict(f2.VoteLeader(_vote_req(addrs[1], addrs[0], 6), None))
        assert v["granted"]
        # stale epochs (at or below the max seen/voted) are refused
        v = _verdict(f2.VoteLeader(_vote_req(addrs[2], addrs[0], 6 - 1), None))
        assert not v["granted"] and v["reason"] == "stale-epoch"
    finally:
        _stop_all(leader, f1, f2)


def test_vote_survives_voter_restart():
    """A bounced voter must not grant the SAME epoch to a second candidate:
    the vote persists in __broker_meta."""
    leader, (f1, f2), addrs = _trio(auto_promote=False)
    try:
        leader.kill()
        if leader.kill_done is not None:
            leader.kill_done.wait(10)
        v = _verdict(f2.VoteLeader(_vote_req(addrs[1], addrs[0], 7), None))
        assert v["granted"]
        inner = f2.log
        f2.stop()
        f2b = LogServer(inner, port=int(addrs[2].rsplit(":", 1)[1]),
                        follower_of=addrs[0], config=QUORUM_CFG,
                        quorum_peers=addrs)
        # no start() needed: the vote table is recovered at construction
        v = _verdict(f2b.VoteLeader(_vote_req(addrs[2], addrs[0], 7), None))
        assert not v["granted"] and v["reason"] in ("already-voted",
                                                    "stale-epoch")
        v = _verdict(f2b.VoteLeader(_vote_req(addrs[1], addrs[0], 7), None))
        assert v["granted"] or v["reason"] == "stale-epoch"
        f2 = f2b
    finally:
        _stop_all(leader, f1, f2)


# -- majority promotion ---------------------------------------------------------------


def test_majority_promotion_on_leader_kill_and_cluster_repoint():
    leader, (f1, f2), addrs = _trio()
    client = GrpcLogTransport(",".join(addrs), config=QUORUM_CFG)
    try:
        client.create_topic(TopicSpec("ev", 1))
        acked = _commit_n(client, "t-q", 6, prefix="pre")
        leader.kill()
        winner = _wait_leader([f1, f2])
        loser = f2 if winner is f1 else f1
        # the winner minted its epoch from a strict majority (flight proof)
        types = [e["type"] for e in winner.flight.events()]
        assert "quorum.win" in types
        assert winner.epoch >= 2
        # the losing follower repointed: stream + prober now aim at the winner
        winner_addr = winner.advertised
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if (loser.leader_hint == winner_addr
                    and loser._follower_of == winner_addr
                    and loser._leader_prober is not None
                    and loser._leader_prober.target == winner_addr):
                break
            time.sleep(0.05)
        assert loser._follower_of == winner_addr, "loser never repointed"
        assert loser._leader_prober.target == winner_addr
        # cluster keeps serving exactly-once through the new leader
        acked += _commit_n(client, "t-q", 6, prefix="post")
        _assert_exactly_once(winner.log, "ev", acked)
        status = client.broker_status()
        assert status["quorum"]["cluster_size"] == 3
        assert status["quorum"]["majority"] == 2
    finally:
        client.close()
        _stop_all(leader, f1, f2)


def test_candidate_without_majority_stands_down_no_split_brain():
    """vote-blackhole on every voter: a candidate that cannot reach a quorum
    must NEVER promote on its own liveness view — then, once votes flow
    again, the re-armed prober drives a successful campaign."""
    leader, (f1, f2), addrs = _trio(extra={
        "surge.log.quorum.vote-rounds": 3})
    try:
        for f in (f1, f2):
            f.faults = FaultPlane(
                [FaultRule(site="rpc.VoteLeader", action="drop", times=None)])
            f.faults.on_crash = lambda point: None
        leader.kill()
        # both campaign, neither can reach the other's vote: both stand down
        deadline = time.monotonic() + 8
        stood_down = set()
        while time.monotonic() < deadline and len(stood_down) < 2:
            for f in (f1, f2):
                if any(e["type"] == "quorum.stand-down"
                       for e in f.flight.events()):
                    stood_down.add(id(f))
            assert f1.role == "follower" and f2.role == "follower", \
                "a minority candidate promoted (split-brain window!)"
            time.sleep(0.05)
        assert len(stood_down) == 2, "candidates never stood down"
        # heal the vote path: the reset probers re-declare and a campaign wins
        for f in (f1, f2):
            f.faults.disarm()
        winner = _wait_leader([f1, f2], timeout=30.0)
        assert winner.role == "leader"
    finally:
        _stop_all(leader, f1, f2)


# -- quorum acks & high-watermark -----------------------------------------------------


def test_quorum_acks_mask_failing_follower():
    """min-insync-acks=2 in a 3-broker cluster: commits ack off the leader +
    ONE follower while ships to the other are blackholed — well inside the
    ISR timeout that acks=all would have to wait out."""
    leader, (f1, f2), addrs = _trio(auto_promote=False, extra={
        "surge.log.replication.min-insync-acks": 2,
        "surge.log.replication-isr-timeout-ms": 60_000,  # stays "in sync"
    })
    client = GrpcLogTransport(addrs[0], config=QUORUM_CFG)
    try:
        client.create_topic(TopicSpec("ev", 1))
        acked = _commit_n(client, "t-acks", 3, prefix="both")
        # blackhole ships to f2 only; f2 stays in the (60s-timeout) ISR
        leader.faults = FaultPlane(
            [FaultRule(site=f"ship.{addrs[2]}", action="drop", times=None)])
        leader.faults.on_crash = lambda point: None
        t0 = time.monotonic()
        acked += _commit_n(client, "t-acks", 3, prefix="quorum", timeout=20.0)
        elapsed = time.monotonic() - t0
        assert elapsed < 10.0, (
            f"quorum acks took {elapsed:.1f}s — they waited on the "
            "blackholed follower")
        # the quorum replica serves everything; exactly-once on the leader
        _assert_exactly_once(leader.log, "ev", acked)
        c1 = GrpcLogTransport(addrs[1], config=QUORUM_CFG)
        try:
            assert [r.value for r in c1.read("ev", 0)] == acked
            assert c1.high_watermark("ev", 0) == len(acked)
        finally:
            c1.close()
        # the blackholed follower holds (and therefore serves) only the
        # pre-fault prefix — nothing beyond its shipped high-watermark
        c2 = GrpcLogTransport(addrs[2], config=QUORUM_CFG)
        try:
            assert [r.value for r in c2.read("ev", 0)] == acked[:3]
            assert c2.high_watermark("ev", 0) == 3
        finally:
            c2.close()
        status = leader.replication_status()
        assert status["min_insync_acks"] == 2
    finally:
        client.close()
        _stop_all(leader, f1, f2)


def read_reply(server, request) -> pb.ReadReply:
    """Normalize an in-process Read answer: the native reply leg hands back
    pre-serialized ReadReply bytes (what the wire carries); the Python path
    hands back the message."""
    reply = server.Read(request, None)
    if isinstance(reply, bytes):
        return pb.ReadReply.FromString(reply)
    return reply


def test_hwm_gate_clamps_follower_reads_and_end_offset_reports_it():
    """The gate itself, deterministically: a follower holding records ABOVE
    its shipped high-watermark serves only the records below it — applied
    but not provably quorum-held stays invisible, like an open txn."""
    (port,) = free_ports(1)
    f = LogServer(InMemoryLog(), port=port, follower_of="127.0.0.1:1",
                  config=QUORUM_CFG)
    try:
        f.log.create_topic(TopicSpec("ev", 1))
        f.log.append_verbatim([rec("ev", f"k{o}", f"v{o}".encode(), offset=o)
                               for o in range(4)])
        f._hwm[("ev", 0)] = 2  # the last shipped quorum frontier
        reply = read_reply(f, pb.ReadRequest(topic="ev", partition=0,
                                             from_offset=0))
        assert [m.value for m in reply.records] == [b"v0", b"v1"]
        off = f.EndOffset(pb.OffsetRequest(topic="ev", partition=0), None)
        assert off.end_offset == 4 and off.high_watermark == 2
        # an UNGATED partition (no hwm ever shipped) keeps PR-4 semantics
        f.log.create_topic(TopicSpec("legacy", 1))
        f.log.append_verbatim([rec("legacy", "k", b"v", offset=0)])
        reply = read_reply(f, pb.ReadRequest(topic="legacy", partition=0,
                                             from_offset=0))
        assert [m.value for m in reply.records] == [b"v"]
        # BrokerStatus surfaces the per-partition hwm (chaos.py's view)
        assert f.broker_status()["high_watermarks"]["ev"]["0"] == 2
    finally:
        f.stop()


def test_follower_reads_see_commit_the_moment_it_acks():
    """Read-your-committed-writes on followers: the finalize pass beacons
    the raised hwm BEFORE waking the committer, so a read against either
    follower immediately after the ack must already see the record."""
    leader, (f1, f2), addrs = _trio(auto_promote=False)
    client = GrpcLogTransport(addrs[0], config=QUORUM_CFG)
    readers = [GrpcLogTransport(a, config=QUORUM_CFG) for a in addrs[1:]]
    try:
        client.create_topic(TopicSpec("ev", 1))
        p = client.transactional_producer("t-ryw")
        for i in range(8):
            p.begin()
            p.send(rec("ev", f"k{i}", f"v{i}".encode()))
            p.commit()
            for r in readers:
                values = [x.value for x in r.read("ev", 0)]
                assert f"v{i}".encode() in values, (
                    f"commit {i} acked but invisible on follower "
                    f"{r.target} (hwm beacon lost the race)")
    finally:
        client.close()
        for r in readers:
            r.close()
        _stop_all(leader, f1, f2)


# -- slices over the wire -------------------------------------------------------------


def test_fetch_and_install_slice_rpcs():
    leader, (f1, f2), addrs = _trio(auto_promote=False)
    client = GrpcLogTransport(addrs[0], config=QUORUM_CFG)
    try:
        client.create_topic(TopicSpec("ev", 1))
        acked = _commit_n(client, "t-slice", 10)
        reply = client._calls["FetchSlice"](pb.ReadRequest(
            topic="ev", partition=0, from_offset=2, has_max=True,
            max_records=5), timeout=5.0)
        assert reply.ok
        header, records = decode_partition_slice(bytes(
            reply.records[0].value))
        assert header["from"] == 2 and len(records) == 5
        assert records[0].offset == 2
        # a leader refuses installs (foreign offsets would fork its log)
        install_req = pb.TxnRequest(op="install", records=[pb.RecordMsg(
            topic="ev", partition=0, has_value=True,
            value=bytes(reply.records[0].value))])
        refused = leader.InstallSlice(install_req, None)
        assert not refused.ok and "leader" in refused.error
        # a fresh standby ingests slices (idempotent over what it holds)
        (sport,) = free_ports(1)
        standby = LogServer(InMemoryLog(), port=sport, config=QUORUM_CFG,
                            follower_of=addrs[0])
        try:
            standby.log.create_topic(TopicSpec("ev", 1))
            # gap refused: the slice starts past the standby's end
            refused = standby.InstallSlice(install_req, None)
            assert not refused.ok and "gap" in refused.error
            full = client._calls["FetchSlice"](pb.ReadRequest(
                topic="ev", partition=0, from_offset=0), timeout=5.0)
            ok = standby.InstallSlice(pb.TxnRequest(op="install", records=[
                pb.RecordMsg(topic="ev", partition=0, has_value=True,
                             value=bytes(full.records[0].value))]), None)
            assert ok.ok
            assert [r.value for r in standby.log.read("ev", 0)] == acked
        finally:
            standby.stop()
    finally:
        client.close()
        _stop_all(leader, f1, f2)


def test_install_slice_accepts_vouched_compaction_hole():
    """A slice read FROM the destination's end whose head records were
    compacted away at the source carries ``base <= end`` — the installer
    must ingest past the hole (state topics ARE compacted; refusing would
    abort every handoff after a compaction pass). The same gap UNVOUCHED
    (no base: could be genuinely missing records) stays refused."""
    (sport,) = free_ports(1)
    standby = LogServer(InMemoryLog(), port=sport, config=QUORUM_CFG,
                        follower_of="127.0.0.1:9")  # never started: no probes
    try:
        standby.log.create_topic(TopicSpec("ev", 1))
        head = [rec("ev", f"k{i}", f"v{i}".encode(), offset=i)
                for i in range(5)]
        ok = standby.InstallSlice(pb.TxnRequest(records=[pb.RecordMsg(
            topic="ev", partition=0, has_value=True,
            value=encode_partition_slice(head, "ev", 0, base=0))]), None)
        assert ok.ok, ok.error
        # offsets 5..6 compacted away at the source; the shipper read from
        # the destination's end (5), so the hole is vouched by base=5
        tail = [rec("ev", f"k{i}", f"v{i}".encode(), offset=i)
                for i in (7, 8, 9)]
        unvouched = standby.InstallSlice(pb.TxnRequest(records=[
            pb.RecordMsg(topic="ev", partition=0, has_value=True,
                         value=encode_partition_slice(tail, "ev", 0))]), None)
        assert not unvouched.ok and "gap" in unvouched.error
        vouched = standby.InstallSlice(pb.TxnRequest(records=[
            pb.RecordMsg(topic="ev", partition=0, has_value=True,
                         value=encode_partition_slice(tail, "ev", 0,
                                                      base=5))]), None)
        assert vouched.ok, vouched.error
        assert [r.offset for r in standby.log.read("ev", 0)] == [
            0, 1, 2, 3, 4, 7, 8, 9]
    finally:
        standby.stop()


def test_catch_up_uses_slice_lane():
    leader, (f1, f2), addrs = _trio(auto_promote=False)
    client = GrpcLogTransport(addrs[0], config=QUORUM_CFG)
    try:
        client.create_topic(TopicSpec("ev", 2))
        p = client.transactional_producer("t-cu")
        for i in range(30):
            p.begin()
            p.send(rec("ev", f"k{i}", f"v{i}".encode(), partition=i % 2))
            p.commit()
        (sport,) = free_ports(1)
        standby = LogServer(InMemoryLog(), port=sport, config=QUORUM_CFG)
        try:
            copied = standby.catch_up(addrs[0])
            assert copied == 30
            assert standby._catchup_slices, "slice lane silently disabled"
            for part in (0, 1):
                want = [r.value for r in leader.log.read("ev", part)]
                assert [r.value for r in standby.log.read("ev", part)] == want
        finally:
            standby.stop()
    finally:
        client.close()
        _stop_all(leader, f1, f2)


# -- live handoff ---------------------------------------------------------------------


def test_handoff_moves_leadership_under_load_exactly_once():
    leader, (f1, f2), addrs = _trio()
    client = GrpcLogTransport(",".join(addrs), config=QUORUM_CFG)
    admin = GrpcLogTransport(addrs[0], config=QUORUM_CFG)
    try:
        client.create_topic(TopicSpec("ev", 1))
        acked = _commit_n(client, "t-ho", 20, prefix="pre")
        stop = threading.Event()
        side: dict = {"acked": [], "error": None}

        def writer():
            c = GrpcLogTransport(",".join(addrs), config=QUORUM_CFG)
            try:
                i = 0
                while not stop.is_set():
                    side["acked"] += _commit_n(c, "t-ho-live", 1,
                                               prefix=f"live{i}",
                                               timeout=30.0)
                    i += 1
            except Exception as exc:  # noqa: BLE001
                side["error"] = exc
            finally:
                c.close()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.3)
        stats = admin.handoff_partition(addrs[1])
        time.sleep(0.3)
        stop.set()
        t.join(30.0)
        assert side["error"] is None, f"live writer died: {side['error']!r}"
        assert stats["epoch"] >= 2 and stats["fence_ms"] > 0
        # destination leads, the ex-leader demoted IN PLACE (no kill)
        assert f1.role == "leader" and leader.role == "follower"
        assert not leader._handoff_fence
        # planned move: epoch fenced exactly once, writers never lost a byte
        _assert_exactly_once(f1.log, "ev", acked + side["acked"])
        # the non-destination follower repointed to the new leader
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                f2._follower_of != addrs[1]:
            time.sleep(0.05)
        assert f2._follower_of == addrs[1]
        # the flight ring tells the handoff story end to end
        types = [e["type"] for e in leader.flight.events()]
        for expected in ("handoff.start", "handoff.fence", "handoff.done"):
            assert expected in types
    finally:
        client.close()
        admin.close()
        _stop_all(leader, f1, f2)


def test_handoff_crash_pre_promote_fails_clean_failover_takes_over():
    """Kill the old leader at crash.handoff.pre-promote (tail shipped, dest
    NOT yet promoted): no second leader is minted by the broken handoff, and
    the normal prober-driven failover path recovers the cluster."""
    lport, fport = free_ports(2)
    laddr, faddr = f"127.0.0.1:{lport}", f"127.0.0.1:{fport}"
    follower = LogServer(InMemoryLog(), port=fport, follower_of=laddr,
                         auto_promote=True, config=QUORUM_CFG)
    follower.start()
    leader = LogServer(InMemoryLog(), port=lport, replicate_to=[faddr],
                       config=QUORUM_CFG)
    leader.start()
    client = GrpcLogTransport(f"{laddr},{faddr}", config=QUORUM_CFG)
    try:
        client.create_topic(TopicSpec("ev", 1))
        acked = _commit_n(client, "t-hc", 8)
        client.arm_faults("handoff-crash-pre-promote", seed=1)
        admin = GrpcLogTransport(laddr, config=QUORUM_CFG)
        with pytest.raises(Exception):
            admin.handoff_partition(faddr, timeout=20.0)
        admin.close()
        assert leader._dead, "crash point never fired"
        assert follower.role != "leader" or follower.epoch >= 2
        # the prober path takes over: the follower promotes normally
        deadline = time.monotonic() + 20
        while follower.role != "leader" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert follower.role == "leader"
        _assert_exactly_once(follower.log, "ev", acked)
        acked += _commit_n(client, "t-hc", 4, prefix="after")
        _assert_exactly_once(follower.log, "ev", acked)
    finally:
        client.close()
        _stop_all(leader, follower)


# -- 3-broker chaos: double failure ---------------------------------------------------


def _double_failure_round(seed: int, commits: int = 10) -> None:
    """Kill the leader, let a majority elect a successor, restart the dead
    broker as a follower, then kill the NEW leader while the restarted one
    may still be catching up: a second majority (2 of 3, the relit broker
    voting) must elect again — 0 lost / 0 duplicated across both failovers,
    merged flight timeline complete, at most one promotion per epoch.

    min-insync-acks=2: every acked commit provably lives on two of the
    three replicas — the durability posture that makes 0-lost possible at
    all across a double failure (with the PR-4 default a freshly-promoted
    leader whose ISR shrank to itself could ack a commit and die with it)
    — and the VoteLeader up-to-date check then guarantees the elected
    successor is a replica that holds them."""
    leader, (f1, f2), addrs = _trio(extra={
        "surge.log.replication.min-insync-acks": 2})
    relit = None
    client = GrpcLogTransport(",".join(addrs), config=QUORUM_CFG)
    try:
        client.create_topic(TopicSpec("ev", 1))
        client.arm_faults(json.dumps({"rules": [
            {"site": "rpc.Transact", "action": "reorder", "p": 0.15,
             "times": None, "delay_ms": 20.0},
            {"site": "ship.*", "action": "drop", "p": 0.1, "times": None},
        ]}), seed=seed)
        acked = _commit_n(client, f"t-df-{seed}", commits, prefix="p1",
                          timeout=60.0)
        leader.kill()
        if leader.kill_done is not None:
            leader.kill_done.wait(10)
        w1 = _wait_leader([f1, f2], timeout=30.0)
        acked += _commit_n(client, f"t-df-{seed}", commits, prefix="p2",
                           timeout=60.0)
        # restart the first casualty as a follower of the new leader (same
        # inner log + flight ring: the timeline keeps one story per broker)
        relit = LogServer(leader.log, port=int(addrs[0].rsplit(":", 1)[1]),
                          follower_of=w1.advertised, auto_promote=True,
                          config=QUORUM_CFG, quorum_peers=addrs,
                          flight=leader.flight)
        relit.start()
        # second failure: kill the new leader while the relit broker may
        # still be mid-catch-up — the surviving pair is a strict majority
        w1.kill()
        if w1.kill_done is not None:
            w1.kill_done.wait(10)
        survivors = [s for s in (relit, f1, f2) if s is not w1]
        w2 = _wait_leader(survivors, timeout=40.0)
        acked += _commit_n(client, f"t-df-{seed}", commits, prefix="p3",
                           timeout=90.0)
        _assert_exactly_once(w2.log, "ev", acked)
        # merged story from every broker's black box
        from surge_tpu.observability import merge_dumps

        merged = merge_dumps([leader.flight.dump(), f1.flight.dump(),
                              f2.flight.dump()])
        promotions = [e for e in merged if e["type"] == "role.promote"]
        assert len(promotions) >= 2
        epochs = [e["epoch"] for e in promotions]
        assert len(epochs) == len(set(epochs)), (
            f"two promotions minted the same epoch: {epochs} — "
            "split brain (two acking leaders in one epoch)")
        wins = [e for e in merged if e["type"] == "quorum.win"]
        assert len(wins) >= 2, "promotions happened without majorities"
    finally:
        client.close()
        _stop_all(*(s for s in (leader, relit, f1, f2) if s is not None))


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_double_failure_deterministic_seeds(seed):
    """Tier-1 fast variant of the cluster soak: three fixed seeds, two
    sequential leader kills each, majority re-election both times."""
    _double_failure_round(seed)


@pytest.mark.slow
def test_cluster_chaos_soak_randomized_schedules():
    """Minutes-long seeded soak across many double-failure schedules."""
    for seed in range(40, 48):
        _double_failure_round(seed, commits=20)


# -- client leader-hint cache (ISSUE 13 satellite) ------------------------------------


def test_client_invalidates_learned_hints_across_two_handoffs():
    """A→B→A: the endpoint learned from the first redirect must be dropped
    on the NEXT redirect (and on connect failure), so a moved-back
    partition never ping-pongs through a broker that may be dead by then."""
    leader, (f1, f2), addrs = _trio(auto_promote=False)
    client = GrpcLogTransport(addrs[0], config=QUORUM_CFG)
    admin = GrpcLogTransport(addrs[0], config=QUORUM_CFG)
    try:
        client.create_topic(TopicSpec("ev", 1))
        acked = _commit_n(client, "t-hint", 4, prefix="a1")
        # handoff A→B: the next commit is redirected and LEARNS B
        admin.handoff_partition(addrs[1])
        acked += _commit_n(client, "t-hint", 4, prefix="b")
        assert client.target == addrs[1]
        assert addrs[1] in client.targets and addrs[1] in client._learned
        # handoff B→A: the redirect back must EVICT the learned B endpoint
        admin2 = GrpcLogTransport(addrs[1], config=QUORUM_CFG)
        admin2.handoff_partition(addrs[0])
        admin2.close()
        acked += _commit_n(client, "t-hint", 4, prefix="a2")
        assert client.target == addrs[0]
        assert addrs[1] not in client.targets, (
            "stale learned hint kept forever — the regression this test "
            "pins down")
        # B dies; commits keep flowing without ever probing the corpse
        f1.kill()
        if f1.kill_done is not None:
            f1.kill_done.wait(10)
        t0 = time.monotonic()
        acked += _commit_n(client, "t-hint", 4, prefix="a3", timeout=10.0)
        assert time.monotonic() - t0 < 8.0, "commits stalled on a dead hint"
        _assert_exactly_once(leader.log, "ev", acked)
    finally:
        client.close()
        admin.close()
        _stop_all(leader, f1, f2)


# -- prober re-arm under repeated elections (ISSUE 13 satellite) ----------------------


def test_prober_rearms_after_repeated_lost_campaigns():
    """A broker that loses N consecutive campaigns (the stand-down path)
    must STILL detect the next real leader death: blackholed votes force
    repeated stand-downs on both followers; once votes flow again a
    campaign wins, and after killing THAT leader back-to-back the
    previously-stood-down broker still participates in the next majority."""
    leader, (f1, f2), addrs = _trio(extra={
        "surge.log.quorum.vote-rounds": 2})
    relit = None
    try:
        for f in (f1, f2):
            f.faults = FaultPlane(
                [FaultRule(site="rpc.VoteLeader", action="drop", times=None)])
            f.faults.on_crash = lambda point: None
        leader.kill()
        if leader.kill_done is not None:
            leader.kill_done.wait(10)
        # both followers campaign and stand down REPEATEDLY (>= 2 cycles
        # each), the prober re-arming after every lost campaign
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            stand_downs = {
                id(f): sum(1 for e in f.flight.events()
                           if e["type"] == "quorum.stand-down")
                for f in (f1, f2)}
            assert f1.role == "follower" and f2.role == "follower", \
                "a minority candidate promoted"
            if all(n >= 2 for n in stand_downs.values()):
                break
            time.sleep(0.1)
        assert all(n >= 2 for n in stand_downs.values()), stand_downs
        for f in (f1, f2):
            assert f._leader_prober is not None
            assert f._leader_prober.rearms >= 2, (
                "prober was not re-armed after each lost campaign")
        # heal the vote path: the re-armed probers drive a winning campaign
        for f in (f1, f2):
            f.faults.disarm()
        w1 = _wait_leader([f1, f2], timeout=30.0)
        loser = f2 if w1 is f1 else f1
        # back-to-back: relight the first casualty, then kill the NEW
        # leader — the broker that lost every earlier campaign must still
        # detect THIS death and reach a majority with the relit voter
        relit = LogServer(leader.log, port=int(addrs[0].rsplit(":", 1)[1]),
                          follower_of=w1.advertised, auto_promote=True,
                          config=QUORUM_CFG, quorum_peers=addrs,
                          flight=leader.flight)
        relit.start()
        time.sleep(0.5)
        w1.kill()
        if w1.kill_done is not None:
            w1.kill_done.wait(10)
        w2 = _wait_leader([loser, relit], timeout=40.0)
        assert w2.role == "leader" and w2.epoch > w1.epoch
        # the cluster still serves exactly-once after the whole ordeal
        client = GrpcLogTransport(",".join(addrs), config=QUORUM_CFG)
        try:
            client.create_topic(TopicSpec("ev", 1))
            acked = _commit_n(client, "t-rearm", 4)
            _assert_exactly_once(w2.log, "ev", acked)
        finally:
            client.close()
    finally:
        _stop_all(*(s for s in (leader, relit, f1, f2) if s is not None))


# -- chaos CLI: cluster & handoff -----------------------------------------------------


def test_chaos_cli_cluster_and_handoff_smoke():
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cli = os.path.join(repo, "tools", "chaos.py")

    def run(*argv):
        out = subprocess.run([sys.executable, cli, *argv],
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, (argv, out.stderr[-500:])
        return out.stdout

    leader, (f1, f2), addrs = _trio(auto_promote=False)
    try:
        cluster_arg = ",".join(addrs)
        out = json.loads(run("cluster", cluster_arg))
        assert out["verdict"] == "ok: exactly one leader"
        assert out["leaders"] == [addrs[0]]
        assert out["brokers"][addrs[1]]["role"] == "follower"
        assert out["brokers"][addrs[0]]["quorum"]["cluster_size"] == 3
        # arm a plan everywhere from one invocation
        out = json.loads(run("cluster", cluster_arg, "--arm", "fsync-hiccup",
                             "--seed", "5"))
        for addr in addrs:
            assert out["brokers"][addr]["faults"]["rules"], addr
        # planned handoff from the CLI
        stats = json.loads(run("handoff", addrs[0], addrs[1]))
        assert stats["to"] == addrs[1] and stats["epoch"] >= 2
        assert f1.role == "leader"
        out = json.loads(run("cluster", cluster_arg))
        assert out["leaders"] == [addrs[1]]
        # kill one broker from the cluster command
        out = json.loads(run("cluster", cluster_arg, "--kill", addrs[2]))
        assert out["brokers"][addrs[2]] == {"killed": True}
        assert f2._dead
    finally:
        _stop_all(leader, f1, f2)
