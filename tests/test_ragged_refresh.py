"""Bucketed ragged refresh dispatch (ISSUE 18): length-bucketed refresh
programs, the ragged Pallas fold tile, and donated slab scatters.

The load-bearing proofs mirror the plane's golden bar: byte-identity vs the
full cold-start replay across evict/re-admit and a partition rebalance, on
cpu AND the forced 8-device mesh, for the bucketed and pallas-ragged arms.
On top of that: the compile-signature set stays bounded by the layout's
bucket table under 100 adversarial rounds (dense and bucketed), a donated
refresh round never surfaces a deleted buffer to any read path (batched
gather, project, evict spill, view fold — with the `donate-refresh` kill
switch as the paired arm), and the steady-ragged shape's padding waste drops
≥ 3x vs the dense rectangle."""

import asyncio

import pytest

from surge_tpu.replay.ledger import ReplayLedger

from tests.test_resident_state import (
    EVT,
    STATE,
    TOPIC,
    Expected,
    append_events,
    cold_restore_bytes,
    make_log,
    part_of,
    wait_caught_up,
)


def make_plane(log, *, capacity=64, ledger=None, mesh=None, overrides=None):
    from surge_tpu.config import default_config
    from surge_tpu.models import counter
    from surge_tpu.replay.resident_state import ResidentStatePlane
    from surge_tpu.serialization import SerializedMessage

    cfg = default_config().with_overrides({
        "surge.replay.resident.capacity": capacity,
        "surge.replay.resident.refresh-interval-ms": 10,
        "surge.replay.batch-size": 16,
        "surge.replay.time-chunk": 8,
        **(overrides or {}),
    })
    return ResidentStatePlane(
        log, TOPIC, counter.make_replay_spec(), config=cfg, mesh=mesh,
        deserialize_event=lambda raw: EVT.read_event(
            SerializedMessage(key="", value=raw)),
        serialize_state=lambda a, s: STATE.write_state(s).value,
        ledger=ledger)


def _refresh_sigs(plane):
    return {s for s in plane._signatures
            if s[0] in ("refresh", "refresh-ragged")}


# -- golden byte-identity: bucketed and pallas-ragged arms ----------------------------


@pytest.mark.parametrize("overrides", [
    {"surge.replay.resident.refresh-dispatch": "bucketed"},
    {"surge.replay.resident.refresh-dispatch": "bucketed",
     "surge.replay.tile-backend": "pallas",
     "surge.replay.dispatch": "select"},
], ids=["bucketed", "bucketed-pallas"])
def test_bucketed_refresh_golden_byte_identity(overrides):
    """Incremental bucketed refresh rounds — across evictions, re-admissions
    AND a partition revoke/re-grant — byte-identical to the full cold-start
    replay, with the round anatomy carrying per-bucket occupancy."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(30)]
        evs = []
        for i, agg in enumerate(aggs):
            evs.extend(exp.events(agg, 3 + i % 5, decrement_every=4))
        append_events(log, evs)
        led = ReplayLedger(name="engine:t")
        plane = make_plane(log, capacity=8, ledger=led, overrides=overrides)
        ragged_arm = overrides.get("surge.replay.tile-backend") == "pallas"
        assert plane._ragged == ragged_arm
        await plane.start()
        try:
            for rnd in range(4):
                evs = []
                for i, agg in enumerate(aggs):
                    if (i + rnd) % 3 == 0:
                        evs.extend(exp.events(agg, 2 + rnd,
                                              decrement_every=3))
                append_events(log, evs)
                await wait_caught_up(plane)
                if rnd == 1:
                    plane.set_partitions([0, 2, 3])
                    assert all(part_of(a) != 1
                               for a in plane.resident_ids())
                    plane.set_partitions([0, 1, 2, 3])
                    await wait_caught_up(plane)
            assert plane.stats["evictions"] > 0
            golden = cold_restore_bytes(log)
            for agg in aggs:
                hit, data = await plane.read_bytes(agg)
                assert hit, agg
                assert data == golden[agg], agg
            assert plane.snapshot_states() == exp.states
            # the ledger carried bucket anatomy: every round names its
            # occupied buckets and the bounded table; lanes never exceed
            # the bucket's pow2 lane capacity
            rounds = [e for e in led.events() if e["type"] == "round"]
            assert rounds and all(e["buckets"] for e in rounds)
            for e in rounds:
                assert e["bucket_table"] == len(plane.bucket_table)
                for bk in e["buckets"]:
                    assert 0 < bk["lanes"] <= bk["lanes_b"]
                    assert (bk["lanes_b"], bk["width"]) in plane.bucket_table
            if ragged_arm:
                assert any(s[0] == "refresh-ragged"
                           for s in plane._signatures)
            assert led.summary()["bucket_programs"] == sum(
                len(e["buckets"]) for e in rounds)
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_mesh_bucketed_golden_byte_identity(mesh8):
    """The bucketed dispatch on the sharded mesh plane: per-shard deals ride
    the pow2 lane buckets and stay byte-identical across evict/re-admit and
    a rebalance (the mesh arm of the tentpole's golden bar)."""
    from tests.test_resident_mesh_plane import _mesh_plane

    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(30)]
        evs = []
        for i, agg in enumerate(aggs):
            evs.extend(exp.events(agg, 3 + i % 5, decrement_every=4))
        append_events(log, evs)
        led = ReplayLedger(name="engine:t")
        plane = _mesh_plane(log, mesh8, capacity=10, ledger=led, overrides={
            "surge.replay.resident.refresh-dispatch": "bucketed"})
        assert plane.capacity == 16 and plane._mesh_local
        await plane.start()
        try:
            for rnd in range(3):
                evs = []
                for i, agg in enumerate(aggs):
                    if (i + rnd) % 3 == 0:
                        evs.extend(exp.events(agg, 2 + rnd,
                                              decrement_every=3))
                append_events(log, evs)
                await wait_caught_up(plane)
                if rnd == 1:
                    plane.set_partitions([0, 2, 3])
                    plane.set_partitions([0, 1, 2, 3])
                    await wait_caught_up(plane)
            assert plane.stats["evictions"] > 0
            golden = cold_restore_bytes(log)
            for agg in aggs:
                hit, data = await plane.read_bytes(agg)
                assert hit and data == golden[agg], agg
            rounds = [e for e in led.events() if e["type"] == "round"]
            assert rounds and all(e["buckets"] for e in rounds)
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- compile-cache bound --------------------------------------------------------------


@pytest.mark.parametrize("dispatch", ["bucketed", "dense"])
def test_compile_cache_bounded_by_bucket_table(dispatch):
    """100 refresh rounds with adversarially varied lane counts and tail
    lengths compile at most len(bucket_table) refresh signatures — shape
    churn cannot blow the jit cache on either dispatch arm, and every
    compiled (lanes_b, width) draws from the table."""
    async def scenario():
        log = make_log()
        exp = Expected()
        plane = make_plane(log, capacity=64, overrides={
            "surge.replay.resident.refresh-dispatch": dispatch})
        plane._ensure_device_state()
        plane.seed_from_log()
        for i in range(100):
            lanes = (i * 7) % 37 + 1
            tail = (i * 3) % 9 + 1
            evs = []
            for j in range(lanes):
                evs.extend(exp.events(f"agg-{j}", tail))
            append_events(log, evs)
            assert await plane._refresh_once()
        sigs = _refresh_sigs(plane)
        assert 1 <= len(sigs) <= len(plane.bucket_table), sigs
        for s in sigs:
            assert (s[1], s[2]) in plane.bucket_table, s
        await plane.stop()

    asyncio.run(scenario())


# -- donation safety ------------------------------------------------------------------


@pytest.mark.parametrize("donate", [True, False], ids=["donated", "copying"])
def test_donated_refresh_keeps_every_read_path_live(donate):
    """After donated refresh rounds the plane's handle is rebound to the
    donated result: batched gathers, project, the evict spill d2h and the
    view fold all see the NEW slab and no deleted-buffer error surfaces.
    The donate-refresh=False arm is the kill switch: identical results."""
    from surge_tpu.replay.query import Aggregate, ScanQuery
    from surge_tpu.replay.views import MaterializedViews, ViewDef
    from surge_tpu.models import counter
    from surge_tpu.config import default_config

    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(30)]
        evs = []
        for i, agg in enumerate(aggs):
            evs.extend(exp.events(agg, 2 + i % 4, decrement_every=3))
        append_events(log, evs)
        overrides = {"surge.replay.donate-refresh": donate,
                     "surge.query.chunk-events": 1024}
        # capacity 8 << 30 aggregates: every round evicts (the spill d2h
        # reads the slab the round just donated)
        plane = make_plane(log, capacity=8, overrides=overrides)
        assert plane._donate_refresh is donate
        cfg = default_config().with_overrides(overrides)
        views = MaterializedViews(counter.make_replay_spec(), config=cfg)
        plane.attach_views(views)
        plane.register_view(ViewDef(
            name="totals",
            query=ScanQuery(aggregates=(Aggregate("count"),
                                        Aggregate("sum", "increment_by")))))
        await plane.start()
        try:
            for rnd in range(3):
                evs = []
                for i, agg in enumerate(aggs):
                    if (i + rnd) % 2 == 0:
                        evs.extend(exp.events(agg, 2, decrement_every=2))
                append_events(log, evs)
                await wait_caught_up(plane)
                # read paths interleaved with donating rounds: batched
                # gather + the project alias, both must see the live slab
                got = await plane.read_many(aggs)
                assert got == {a: exp.states[a] for a in aggs}
                proj = await plane.project(aggs[:5])
                assert proj == {a: exp.states[a] for a in aggs[:5]}
            assert plane.stats["evictions"] > 0
            golden = cold_restore_bytes(log)
            for agg in aggs:
                hit, data = await plane.read_bytes(agg)
                assert hit and data == golden[agg], agg
            # the view fold rode the same donated rounds
            snap = views.snapshot("totals")
            assert snap["rows"], snap
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- the waste reduction itself -------------------------------------------------------


def test_steady_ragged_waste_drops_3x_bucketed():
    """The acceptance number on the steady-ragged shape (10 lanes, short
    tails): the bucketed arm's padding-waste ratio is ≥ 3x below the dense
    rectangle's on the identical workload."""
    async def one_round(dispatch):
        log = make_log()
        exp = Expected()
        led = ReplayLedger(name="engine:t")
        plane = make_plane(log, ledger=led, overrides={
            "surge.replay.resident.refresh-dispatch": dispatch})
        plane._ensure_device_state()
        plane.seed_from_log()
        evs = []
        for i in range(10):
            evs.extend(exp.events(f"agg-{i}", 5))
        append_events(log, evs)
        assert await plane._refresh_once()
        s = led.summary()
        assert s["events"] == 50 and s["occupied_slots"] == 50
        await plane.stop()
        return s["waste_ratio"]

    async def scenario():
        dense = await one_round("dense")
        bucketed = await one_round("bucketed")
        assert dense / bucketed >= 3.0, (dense, bucketed)
        assert bucketed < 3.0, bucketed

    asyncio.run(scenario())


# -- CLI rendering --------------------------------------------------------------------


def test_chaos_renders_bucket_anatomy():
    """`chaos.py replay-ledger`'s stderr bucket table off a dumped envelope:
    per-bucket fill/waste lines for rounds that carried anatomy, empty for
    dense/pre-bucketing dumps (stdout stays the parseable JSON envelope)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import chaos

    led = ReplayLedger(name="engine:t")
    led.record_round(
        events=50, lanes=10, windows=2, dispatched=128, occupied=50,
        batch=8, width=8, feed_us=10.0, encode_us=5.0, dispatch_us=100.0,
        bucket_table=12,
        buckets=[{"width": 4, "lanes_b": 8, "lanes": 6, "windows": 1,
                  "dispatched": 32, "occupied": 20, "ragged": True},
                 {"width": 8, "lanes_b": 8, "lanes": 4, "windows": 1,
                  "dispatched": 96, "occupied": 30, "ragged": None}])
    text = chaos._render_bucket_anatomy(led.dump())
    assert "bucket_table=12" in text
    assert "w4×8: lanes 6/8" in text and "ragged" in text
    assert "w8×8: lanes 4/8" in text
    # a dense dump renders nothing
    dense = ReplayLedger(name="engine:t")
    dense.record_round(events=50, lanes=10, windows=1, dispatched=512,
                       occupied=50, batch=64, width=8, feed_us=1.0,
                       encode_us=1.0, dispatch_us=1.0)
    assert chaos._render_bucket_anatomy(dense.dump()) == ""
