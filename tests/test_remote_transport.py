"""gRPC node transport: two engine nodes forwarding envelopes over real sockets.

The multi-jvm routing spec analog (SurgePartitionRouterImplMultiJvmSpec, SURVEY.md
§4.6), with gRPC-over-loopback replacing Akka remoting: ask semantics (success /
rejection / failure / state) must survive the wire in the app's own formats."""

import asyncio

import pytest

from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
from surge_tpu.engine.entity import CommandFailure, CommandRejected, CommandSuccess
from surge_tpu.engine.partition import HostPort, PartitionTracker
from surge_tpu.log import InMemoryLog
from surge_tpu.models import counter
from surge_tpu.remote import GrpcRemoteDeliver, NodeTransportServer

A = HostPort("node-a", 1)
B = HostPort("node-b", 2)

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.engine.num-partitions": 4,
})


def make_logic(with_commands=True):
    return SurgeCommandBusinessLogic(
        aggregate_name="counter", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting(),
        command_format=counter.command_formatting() if with_commands else None)


async def _two_nodes(with_commands=True):
    log = InMemoryLog()
    tracker = PartitionTracker()
    engines, servers, delivers = {}, {}, {}
    for host in (A, B):
        deliver = GrpcRemoteDeliver(make_logic(with_commands))
        delivers[host] = deliver
        engines[host] = create_engine(make_logic(with_commands), log=log, config=CFG,
                                      local_host=host, tracker=tracker,
                                      remote_deliver=deliver)
    for host in (A, B):
        await engines[host].start()
        servers[host] = NodeTransportServer(engines[host])
        port = await servers[host].start()
        for d in delivers.values():
            d.set_address(host, f"127.0.0.1:{port}")
    tracker.update({A: [0, 1], B: [2, 3]})
    return log, tracker, engines, servers, delivers


async def _teardown(engines, servers, delivers):
    for host in (A, B):
        await servers[host].stop()
        await engines[host].stop()
        await delivers[host].close()


def test_cross_node_commands_and_reads():
    async def scenario():
        log, tracker, engines, servers, delivers = await _two_nodes()
        # drive everything from node A; ids on partitions 2..3 cross the wire to B
        remote_hit = 0
        for i in range(30):
            agg = f"agg-{i}"
            r = await engines[A].aggregate_for(agg).send_command(counter.Increment(agg))
            assert isinstance(r, CommandSuccess) and r.state.count == 1, (i, r)
            if engines[A].router.partition_for(agg) in (2, 3):
                remote_hit += 1
        assert remote_hit > 0  # some aggregates really crossed nodes

        # cross-node get_state + apply_events
        remote_agg = next(f"agg-{i}" for i in range(30)
                          if engines[A].router.partition_for(f"agg-{i}") in (2, 3))
        st = await engines[A].aggregate_for(remote_agg).get_state()
        assert st is not None and st.count == 1
        r = await engines[A].aggregate_for(remote_agg).apply_events(
            [counter.CountIncremented(remote_agg, 4, st.version + 1)])
        assert isinstance(r, CommandSuccess) and r.state.count == 5

        # cross-node rejection round-trips as CommandRejected
        r = await engines[A].aggregate_for(remote_agg).send_command(
            counter.FailCommandProcessing(remote_agg, "nope"))
        assert isinstance(r, CommandRejected) and "nope" in str(r.reason)

        # state for a never-touched remote aggregate is None across the wire
        empty = next(f"fresh-{i}" for i in range(50)
                     if engines[A].router.partition_for(f"fresh-{i}") in (2, 3))
        assert await engines[A].aggregate_for(empty).get_state() is None

        await _teardown(engines, servers, delivers)

    asyncio.run(scenario())


def test_traceparent_propagates_across_remote_hop_and_back():
    """One trace follows a command over the wire: the ask span on node A, the
    forward span in A's transport, the receive span in B's server, and B's
    entity span all share one trace id — and the reply resolves the ask."""
    from surge_tpu.tracing import InMemoryTracer

    tracer_a, tracer_b = InMemoryTracer(), InMemoryTracer()
    tracers = {A: tracer_a, B: tracer_b}

    async def scenario():
        log = InMemoryLog()
        tracker = PartitionTracker()
        engines, servers, delivers = {}, {}, {}
        for host in (A, B):
            deliver = GrpcRemoteDeliver(make_logic(), tracer=tracers[host])
            delivers[host] = deliver
            engines[host] = create_engine(
                make_logic(), log=log, config=CFG, local_host=host,
                tracker=tracker, remote_deliver=deliver, tracer=tracers[host])
        for host in (A, B):
            await engines[host].start()
            servers[host] = NodeTransportServer(engines[host])
            port = await servers[host].start()
            for d in delivers.values():
                d.set_address(host, f"127.0.0.1:{port}")
        tracker.update({A: [0, 1], B: [2, 3]})
        remote_agg = next(f"agg-{i}" for i in range(50)
                          if engines[A].router.partition_for(f"agg-{i}") in (2, 3))
        r = await engines[A].aggregate_for(remote_agg).send_command(
            counter.Increment(remote_agg))
        assert isinstance(r, CommandSuccess) and r.state.count == 1
        await _teardown(engines, servers, delivers)

    asyncio.run(scenario())

    ask = tracer_a.spans_named("aggregate-ref.ProcessMessage")[0]
    tid = ask.context.trace_id
    fwd = tracer_a.spans_named("remote.deliver")[0]
    recv = tracer_b.spans_named("transport.receive")[0]
    entity = tracer_b.spans_named("entity.ProcessMessage")[0]
    assert fwd.context.trace_id == tid
    assert recv.context.trace_id == tid  # traceparent survived the wire
    assert recv.parent_id == fwd.context.span_id
    assert entity.context.trace_id == tid
    # ...and back: the forward span closed only after the remote reply resolved
    assert ask.status == "ok" and fwd.end_time is not None
    assert fwd.end_time >= recv.start_time


def test_missing_command_format_fails_fast():
    async def scenario():
        log, tracker, engines, servers, delivers = await _two_nodes(with_commands=False)
        remote_agg = next(f"agg-{i}" for i in range(50)
                          if engines[A].router.partition_for(f"agg-{i}") in (2, 3))
        r = await engines[A].aggregate_for(remote_agg).send_command(
            counter.Increment(remote_agg))
        assert isinstance(r, CommandFailure)
        assert "command_format" in str(r.error)
        await _teardown(engines, servers, delivers)

    asyncio.run(scenario())


def test_unreachable_node_surfaces_failure():
    async def scenario():
        log = InMemoryLog()
        tracker = PartitionTracker()
        deliver = GrpcRemoteDeliver(make_logic())
        deliver.set_address(B, "127.0.0.1:1")  # nothing listens there
        engine = create_engine(make_logic(), log=log, config=CFG, local_host=A,
                               tracker=tracker, remote_deliver=deliver)
        await engine.start()
        tracker.update({A: [0, 1], B: [2, 3]})
        remote_agg = next(f"agg-{i}" for i in range(50)
                          if engine.router.partition_for(f"agg-{i}") in (2, 3))
        r = await engine.aggregate_for(remote_agg).send_command(
            counter.Increment(remote_agg))
        assert isinstance(r, CommandFailure)
        await engine.stop()
        await deliver.close()

    asyncio.run(scenario())


def test_readdressing_a_restarted_node_takes_effect():
    """Regression: set_address must drop the cached channel so a node that came
    back on a new port is reachable immediately."""
    async def scenario():
        log, tracker, engines, servers, delivers = await _two_nodes()
        remote_agg = next(f"agg-{i}" for i in range(50)
                          if engines[A].router.partition_for(f"agg-{i}") in (2, 3))
        r = await engines[A].aggregate_for(remote_agg).send_command(
            counter.Increment(remote_agg))
        assert isinstance(r, CommandSuccess)

        # B's server restarts on a different port
        await servers[B].stop()
        servers[B] = NodeTransportServer(engines[B])
        new_port = await servers[B].start()
        delivers[A].set_address(B, f"127.0.0.1:{new_port}")
        await asyncio.sleep(0)  # let the old channel's close task run

        r = await engines[A].aggregate_for(remote_agg).send_command(
            counter.Increment(remote_agg))
        assert isinstance(r, CommandSuccess) and r.state.count == 2
        await _teardown(engines, servers, delivers)

    asyncio.run(scenario())


def test_server_delivers_to_addressed_partition_without_rerouting():
    """Regression: a forwarded envelope must land in the addressed partition's
    local region even when the receiving node's OWN tracker claims another node
    owns it (diverged trackers mid-rebalance must not ping-pong envelopes)."""
    async def scenario():
        from surge_tpu.remote.transport import pb

        log = InMemoryLog()
        # B has its own tracker whose view says A owns EVERY partition — so a
        # regressed server (router.deliver) would forward back toward A
        tracker_b = PartitionTracker()
        engine_b = create_engine(make_logic(), log=log, config=CFG, local_host=B,
                                 tracker=tracker_b,
                                 remote_deliver=lambda *a: (_ for _ in ()).throw(
                                     AssertionError("envelope bounced back off-node")))
        await engine_b.start()
        tracker_b.update({A: [0, 1, 2, 3]})
        server_b = NodeTransportServer(engine_b)
        await server_b.start()

        req = pb.DeliverRequest(aggregate_id="agg-x", partition=2)
        req.command = counter.command_formatting().write_command(
            counter.Increment("agg-x"))
        reply = await server_b.Deliver(req, None)
        assert reply.outcome == "success", reply
        await server_b.stop()
        await engine_b.stop()

    asyncio.run(scenario())


def test_same_aggregate_forwards_preserve_fifo_order():
    """Regression: two un-awaited sends to one remote aggregate must arrive in
    send order (per-aggregate FIFO across the wire, like local mailbox delivery)."""
    async def scenario():
        log, tracker, engines, servers, delivers = await _two_nodes()
        remote_agg = next(f"agg-{i}" for i in range(50)
                          if engines[A].router.partition_for(f"agg-{i}") in (2, 3))
        ref = engines[A].aggregate_for(remote_agg)
        # fire many sends concurrently; sequence numbers must come back monotonically
        tasks = [asyncio.ensure_future(ref.send_command(counter.Increment(remote_agg)))
                 for _ in range(10)]
        results = await asyncio.gather(*tasks)
        counts = [r.state.count for r in results]
        assert counts == list(range(1, 11)), counts
        await _teardown(engines, servers, delivers)

    asyncio.run(scenario())


def test_empty_apply_events_crosses_wire_as_noop():
    """Regression (r2 advisor): ApplyEvents([]) must still select the protobuf
    oneof — an empty list previously left WhichOneof None and the server failed
    a call that is a successful no-op locally."""
    async def scenario():
        log, tracker, engines, servers, delivers = await _two_nodes()
        remote_agg = next(f"agg-{i}" for i in range(50)
                          if engines[A].router.partition_for(f"agg-{i}") in (2, 3))
        ref = engines[A].aggregate_for(remote_agg)
        await ref.send_command(counter.Increment(remote_agg))
        r = await ref.apply_events([])
        assert isinstance(r, CommandSuccess), r
        assert r.state is not None and r.state.count == 1
        await _teardown(engines, servers, delivers)

    asyncio.run(scenario())


def test_zero_byte_state_success_keeps_existence_across_wire():
    """Regression (r2 advisor): a CommandSuccess whose serialized state is
    legitimately zero bytes (passthrough formats) must not collapse to
    CommandSuccess(None) on the client — existence now travels as has_state."""
    from surge_tpu.engine.entity import Envelope
    from surge_tpu.remote.transport import pb

    class EmptyBytesStateFormat:
        def write_state(self, state):
            from surge_tpu.serialization import SerializedAggregate
            return SerializedAggregate(value=state)  # b"" stays b""

        def read_state(self, value):
            return value

    class StubLogic:
        state_format = EmptyBytesStateFormat()
        command_format = counter.command_formatting()
        event_format = counter.event_formatting()

    class StubRouter:
        def deliver_local(self, partition, aggregate_id, env: Envelope):
            env.reply.set_result(CommandSuccess(b""))  # exists, zero bytes

    class StubEngine:
        logic = StubLogic()
        router = StubRouter()
        config = None

    async def scenario():
        server = NodeTransportServer(StubEngine())
        req = pb.DeliverRequest(aggregate_id="z", partition=0)
        req.command = counter.command_formatting().write_command(
            counter.Increment("z"))
        reply = await server.Deliver(req, None)
        assert reply.outcome == "success"
        assert reply.has_state  # the discriminator, not byte length
        assert reply.state == b""
        # client mapping: has_state=True with empty bytes -> state exists
        deliver = GrpcRemoteDeliver(StubLogic())
        fut = asyncio.get_running_loop().create_future()

        async def fake_call(request, timeout=None):
            return reply

        deliver._calls[A] = fake_call
        await deliver._forward(A, req, Envelope(message=None, reply=fut))
        result = await fut
        assert isinstance(result, CommandSuccess)
        assert result.state == b""  # NOT None

    asyncio.run(scenario())
