"""Dense resident layout + single-round-trip state pull.

The r5 on-chip measurements (BENCH_ONCHIP.json) drove two engine changes:

1. ``surge.replay.resident-layout = dense`` pre-gathers every tile once per
   corpus (the per-lane gather was HALF the on-chip fold wall time);
2. ``replay_resident`` pulls states in ONE device→host fetch — a u16 matrix
   with device-computed fit flags when every column is integer/bool, falling
   back to a wide u32 refetch when a value overflows 16 bits (tunnel d2h is
   ~25 MB/s, 20× slower than h2d, so the pull is the long pole at scale).

These run the dense path explicitly on the CPU backend (where ``auto``
resolves to flat to keep restores bounded-memory).
"""

from __future__ import annotations

import numpy as np
import pytest

from surge_tpu.codec.tensor import encode_events_columnar
from surge_tpu.config import Config
from surge_tpu.models import bank_account as ba
from surge_tpu.models import counter
from surge_tpu.replay.corpus import synth_counter_corpus
from surge_tpu.replay.engine import ReplayEngine


def _replay(layout: str, tile: str, events, **cfg):
    eng = ReplayEngine(counter.make_replay_spec(), config=Config({
        "surge.replay.resident-layout": layout,
        "surge.replay.tile-backend": tile,
        "surge.replay.batch-size": 256,
        "surge.replay.time-chunk": 16,
        **cfg,
    }))
    return eng.replay_resident(eng.prepare_resident(events))


def test_auto_tile_backend_resolves_per_backend():
    """``auto`` must resolve to the scan on CPU hosts (the tree measured ~2×
    slower there) even though counter ships an AssociativeFold; explicit
    ``assoc`` is always honored."""
    eng = ReplayEngine(counter.make_replay_spec())
    assert eng.tile_backend == "xla"  # conftest pins the cpu backend
    eng2 = ReplayEngine(counter.make_replay_spec(), config=Config({
        "surge.replay.tile-backend": "assoc"}))
    assert eng2.tile_backend == "assoc"


@pytest.mark.parametrize("tile", ["xla", "assoc"])
def test_dense_layout_matches_flat(tile):
    """Dense pre-gathered tiles fold to exactly the flat-gather states."""
    corpus = synth_counter_corpus(731, 14_000, seed=5, sort_by_length=True)
    flat = _replay("flat", tile, corpus.events)
    dense = _replay("dense", tile, corpus.events)
    np.testing.assert_array_equal(flat.states["count"], dense.states["count"])
    np.testing.assert_array_equal(flat.states["version"],
                                  dense.states["version"])
    np.testing.assert_array_equal(dense.states["count"], corpus.expected_count)
    np.testing.assert_array_equal(dense.states["version"],
                                  corpus.expected_version)
    assert flat.padded_events == dense.padded_events


def test_narrow_pull_overflow_falls_back_wide():
    """A version past 32767 must trip the device fit flag and refetch wide —
    the u16 fast path can never silently truncate."""
    n = 40_000  # > 2^15 events on one lane -> version overflows int16
    logs = [[counter.CountIncremented("big", 1, k + 1) for k in range(n)],
            [counter.CountIncremented("small", 1, 1)]]
    ev = encode_events_columnar(counter.make_registry(), logs)
    res = _replay("dense", "assoc", ev, **{"surge.replay.time-chunk": 64})
    assert int(res.states["count"][0]) == n
    assert int(res.states["version"][0]) == n  # exact despite the u16 fast path
    assert int(res.states["count"][1]) == 1


def test_dense_layout_with_float_state_pulls_wide():
    """bank_account's f32 balance forces the wide (bitcast u32) pull; dense
    tiles must carry its side column correctly."""
    rng = np.random.default_rng(11)
    vocab = ba.Vocab()
    logs, finals = [], []
    for j in range(37):
        evs = [ba.BankAccountCreated(f"acct-{j}", f"o{j}", "s", 4.25)]
        bal = 4.25
        for _ in range(int(rng.integers(0, 24))):
            bal += 0.25
            evs.append(ba.BankAccountUpdated(f"acct-{j}", bal))
        finals.append(bal)
        logs.append([ba.encode_event(vocab, e) for e in evs])
    ev = encode_events_columnar(ba.make_registry(), logs)
    eng = ReplayEngine(ba.make_replay_spec(), config=Config({
        "surge.replay.resident-layout": "dense",
        "surge.replay.batch-size": 64,
        "surge.replay.time-chunk": 8,
    }))
    res = eng.replay_resident(eng.prepare_resident(ev))
    for j, want in enumerate(finals):
        assert res.states["created"][j]
        np.testing.assert_allclose(res.states["balance"][j], want, rtol=1e-6)
