"""Golden-value replay tests: TPU batched fold ≡ scalar CPU fold (SURVEY.md §4
implication: "golden-value replay tests comparing TPU batched fold vs. scalar CPU fold").
"""

import random

import jax
import numpy as np
import pytest

from surge_tpu.codec import decode_states, encode_events
from surge_tpu.config import Config
from surge_tpu.engine.model import fold_events
from surge_tpu.models import bank_account, counter, shopping_cart
from surge_tpu.replay import ReplayEngine


def scalar_fold_states(model, logs, agg_ids=None):
    out = []
    for i, log in enumerate(logs):
        state = model.initial_state(agg_ids[i] if agg_ids else str(i))
        out.append(fold_events(model, state, log))
    return out


def random_counter_logs(n, max_len, seed=0):
    rng = random.Random(seed)
    logs = []
    for i in range(n):
        seq = 0
        log = []
        for _ in range(rng.randrange(max_len + 1)):
            seq += 1
            kind = rng.randrange(3)
            if kind == 0:
                log.append(counter.CountIncremented(str(i), rng.randrange(1, 4), seq))
            elif kind == 1:
                log.append(counter.CountDecremented(str(i), rng.randrange(1, 4), seq))
            else:
                log.append(counter.NoOpEvent(str(i), seq))
        logs.append(log)
    return logs


def test_counter_dense_golden():
    model = counter.CounterModel()
    logs = random_counter_logs(37, 19, seed=1)
    expected = scalar_fold_states(model, logs)

    eng = ReplayEngine(model.replay_spec())
    enc = encode_events(model.replay_spec().registry, logs)
    res = eng.replay_encoded(enc)

    for i, exp in enumerate(expected):
        exp_count = exp.count if exp else 0
        exp_version = exp.version if exp else 0
        assert int(res.states["count"][i]) == exp_count, f"aggregate {i}"
        assert int(res.states["version"][i]) == exp_version, f"aggregate {i}"


def test_counter_time_chunked_golden():
    """Chunked streaming scan must agree with single-scan results."""
    model = counter.CounterModel()
    logs = random_counter_logs(16, 50, seed=2)
    expected = scalar_fold_states(model, logs)

    cfg = Config(overrides={"surge.replay.time-chunk": 7})
    eng = ReplayEngine(model.replay_spec(), config=cfg)
    enc = encode_events(model.replay_spec().registry, logs)
    res = eng.replay_encoded(enc)
    for i, exp in enumerate(expected):
        assert int(res.states["count"][i]) == (exp.count if exp else 0)


def test_bank_account_golden_with_vocab():
    model = bank_account.BankAccountModel()
    vocab = bank_account.Vocab()
    rng = random.Random(3)
    logs, enc_logs = [], []
    for i in range(25):
        log = []
        if rng.random() < 0.8:
            log.append(bank_account.BankAccountCreated(str(i), f"owner{i}", f"sec{i}", 100.0))
            bal = 100.0
            for _ in range(rng.randrange(6)):
                # quarters only: exactly representable in f32
                delta = rng.randrange(1, 40) * 0.25
                if rng.random() < 0.5 or bal < delta:
                    bal += delta
                    log.append(bank_account.BankAccountUpdated(str(i), bal))
                else:
                    bal -= delta
                    log.append(bank_account.BankAccountUpdated(str(i), bal))
        else:
            # orphan update on a never-created account: must stay None
            log.append(bank_account.BankAccountUpdated(str(i), 42.0))
        logs.append(log)
        enc_logs.append([bank_account.encode_event(vocab, e) for e in log])

    expected = scalar_fold_states(model, logs)
    spec = model.replay_spec()
    eng = ReplayEngine(spec)
    enc = encode_events(spec.registry, enc_logs)
    res = eng.replay_encoded(enc)

    for i, exp in enumerate(expected):
        rec = bank_account.EncodedAccountState(
            created=bool(res.states["created"][i]),
            owner_code=int(res.states["owner_code"][i]),
            security_code_code=int(res.states["security_code_code"][i]),
            balance=float(res.states["balance"][i]))
        got = bank_account.decode_state(vocab, str(i), rec)
        if exp is None:
            assert got is None, f"aggregate {i}"
        else:
            assert got is not None
            assert got.account_owner == exp.account_owner
            assert got.security_code == exp.security_code
            assert got.balance == pytest.approx(exp.balance)


def random_cart_logs(n, seed=0, max_len=30):
    rng = random.Random(seed)
    model = shopping_cart.CartModel()
    logs = []
    for i in range(n):
        # generate through the command path so logs are semantically valid
        state = None
        log = []
        for _ in range(rng.randrange(max_len)):
            if state is not None and state.checked_out:
                break
            kind = rng.random()
            try:
                if kind < 0.6:
                    cmd = shopping_cart.AddItem(str(i), rng.randrange(1, 100),
                                                rng.randrange(1, 4), rng.randrange(100, 5000))
                elif kind < 0.9:
                    cmd = shopping_cart.RemoveItem(str(i), rng.randrange(1, 100),
                                                   rng.randrange(1, 3), rng.randrange(100, 5000))
                else:
                    cmd = shopping_cart.Checkout(str(i))
                events = model.process_command(state, cmd)
            except Exception:
                continue
            for ev in events:
                state = model.handle_event(state, ev)
                log.append(ev)
        logs.append(log)
    return logs


def test_shopping_cart_ragged_golden():
    model = shopping_cart.CartModel()
    logs = random_cart_logs(53, seed=5)
    expected = scalar_fold_states(model, logs)

    cfg = Config(overrides={"surge.replay.length-buckets": "4,8,16,32"})
    eng = ReplayEngine(model.replay_spec(), config=cfg)
    res = eng.replay_ragged(logs)

    assert res.num_aggregates == len(logs)
    assert res.num_events == sum(len(l) for l in logs)
    for i, exp in enumerate(expected):
        assert int(res.states["item_count"][i]) == (exp.item_count if exp else 0)
        assert int(res.states["total_cents"][i]) == (exp.total_cents if exp else 0)
        assert bool(res.states["checked_out"][i]) == (exp.checked_out if exp else False)


def test_replay_stream_carries_state_across_chunks():
    model = counter.CounterModel()
    logs = random_counter_logs(8, 40, seed=7)
    expected = scalar_fold_states(model, logs)
    spec = model.replay_spec()

    # split each log into time windows of 10 and encode each window separately
    def chunks():
        t = max(len(l) for l in logs)
        for start in range(0, t, 10):
            window = [l[start:start + 10] for l in logs]
            yield encode_events(spec.registry, window, pad_to=10)

    eng = ReplayEngine(spec)
    res = eng.replay_stream(chunks(), batch=len(logs))
    for i, exp in enumerate(expected):
        assert int(res.states["count"][i]) == (exp.count if exp else 0)
    assert res.num_events == sum(len(l) for l in logs)


# mesh tests take the tests/conftest.py `mesh8` fixture instead of the old
# `skipif device_count < 8` marker: a broken device forcing must FAIL the
# multi-device proofs loudly, never silently skip them out of tier-1


def test_mesh_sharded_replay_golden(mesh8):
    """B sharded over an 8-device CPU mesh must give identical results."""
    mesh = mesh8
    model = counter.CounterModel()
    logs = random_counter_logs(100, 12, seed=9)
    expected = scalar_fold_states(model, logs)

    eng = ReplayEngine(model.replay_spec(), mesh=mesh)
    enc = encode_events(model.replay_spec().registry, logs)
    res = eng.replay_encoded(enc)
    for i, exp in enumerate(expected):
        assert int(res.states["count"][i]) == (exp.count if exp else 0)
        assert int(res.states["version"][i]) == (exp.version if exp else 0)


def test_mesh_sharded_resident_replay_golden(mesh8):
    """The resident tile-loop design across an 8-device CPU mesh: identical
    states to the scalar fold, in original order, via one shard_map dispatch
    per granularity (no collectives — lanes are independent)."""
    from surge_tpu.codec.tensor import encode_events_columnar

    mesh = mesh8
    model = counter.CounterModel()
    logs = random_counter_logs(517, 40, seed=13)  # ragged, not device-aligned
    expected = scalar_fold_states(model, logs)

    cfg = Config(overrides={"surge.replay.batch-size": 128,
                            "surge.replay.time-chunk": 16})
    eng = ReplayEngine(model.replay_spec(), config=cfg, mesh=mesh)
    colev = encode_events_columnar(model.replay_spec().registry, logs)
    sharded = eng.prepare_resident_sharded(colev)
    res = eng.replay_resident_sharded(sharded)
    assert res.num_events == sum(len(l) for l in logs)
    for i, exp in enumerate(expected):
        assert int(res.states["count"][i]) == (exp.count if exp else 0), i
        assert int(res.states["version"][i]) == (exp.version if exp else 0), i

    # resume: fold the first half, carry into the second half
    cut = [len(l) // 2 for l in logs]
    first = encode_events_columnar(model.replay_spec().registry,
                                   [l[:c] for l, c in zip(logs, cut)])
    second = encode_events_columnar(model.replay_spec().registry,
                                    [l[c:] for l, c in zip(logs, cut)])
    r1 = eng.replay_resident_sharded(eng.prepare_resident_sharded(first))
    r2 = eng.replay_resident_sharded(eng.prepare_resident_sharded(second),
                                     init_carry=r1.states,
                                     ordinal_base=np.asarray(cut, np.int32))
    for i, exp in enumerate(expected):
        assert int(r2.states["count"][i]) == (exp.count if exp else 0), i


def test_mesh_sharded_resident_bank_account_side_columns(mesh8):
    """bank_account on the sharded resident path: float side columns ride the
    per-device slabs, and handlers returning literal columns (created=True)
    must compile under shard_map (VMA divergence across switch branches)."""
    from surge_tpu.codec.tensor import encode_events_columnar

    mesh = mesh8
    model = bank_account.BankAccountModel()
    vocab = bank_account.Vocab()
    rng = random.Random(4)
    logs, enc_logs = [], []
    for i in range(85):
        log = [bank_account.BankAccountCreated(str(i), f"o{i}", "s", 100.0)]
        bal = 100.0
        for _ in range(rng.randrange(0, 8)):
            bal += rng.randrange(1, 20) * 0.25
            log.append(bank_account.BankAccountUpdated(str(i), bal))
        logs.append(log)
        enc_logs.append([bank_account.encode_event(vocab, e) for e in log])
    expected = scalar_fold_states(model, logs)

    eng = ReplayEngine(model.replay_spec(), config=Config(overrides={
        "surge.replay.batch-size": 64, "surge.replay.time-chunk": 8}),
        mesh=mesh)
    colev = encode_events_columnar(model.replay_spec().registry, enc_logs)
    res = eng.replay_resident_sharded(eng.prepare_resident_sharded(colev))
    for i, exp in enumerate(expected):
        assert float(res.states["balance"][i]) == pytest.approx(exp.balance), i
        assert bool(res.states["created"][i]), i


def test_mesh_sharded_resident_small_tiles_fold_once(mesh8):
    """800 single-event lanes on 8 devices: per device 100 active lanes with
    bs=128/bs_small=64 ⇒ every window needs TWO small tiles. Each event must
    fold exactly once (a small tile dispatched through the big-bs program
    would overlap/clamp its lane slices and double-fold)."""
    from surge_tpu.codec.tensor import encode_events_columnar

    mesh = mesh8
    model = counter.CounterModel()
    logs = [[counter.CountIncremented(f"a{i}", 1, 1)] for i in range(800)]

    cfg = Config(overrides={"surge.replay.batch-size": 128,
                            "surge.replay.time-chunk": 16})
    eng = ReplayEngine(model.replay_spec(), config=cfg, mesh=mesh)
    colev = encode_events_columnar(model.replay_spec().registry, logs)
    res = eng.replay_resident_sharded(eng.prepare_resident_sharded(colev))
    assert all(int(c) == 1 for c in res.states["count"]), \
        np.unique(np.asarray(res.states["count"]))


def test_mesh_sharded_resident_pallas_golden(mesh8):
    """The Pallas tile-scan kernel under shard_map (``tile-backend = pallas``
    inside the sharded fold's per-device tile loop): byte-identical states to
    the scalar fold, including a resumed fold with ordinal bases."""
    from surge_tpu.codec.tensor import encode_events_columnar

    model = counter.CounterModel()
    logs = random_counter_logs(233, 37, seed=17)  # ragged, not device-aligned
    expected = scalar_fold_states(model, logs)

    cfg = Config(overrides={"surge.replay.batch-size": 128,
                            "surge.replay.time-chunk": 16,
                            "surge.replay.tile-backend": "pallas",
                            "surge.replay.dispatch": "select"})
    eng = ReplayEngine(model.replay_spec(), config=cfg, mesh=mesh8)
    spec = model.replay_spec()
    colev = encode_events_columnar(spec.registry, logs)
    res = eng.replay_resident_sharded(eng.prepare_resident_sharded(colev))
    for i, exp in enumerate(expected):
        assert int(res.states["count"][i]) == (exp.count if exp else 0), i
        assert int(res.states["version"][i]) == (exp.version if exp else 0), i

    # resume: the kernel's ord_rel leg must continue derived ordinals
    cut = [len(l) // 2 for l in logs]
    first = encode_events_columnar(spec.registry,
                                   [l[:c] for l, c in zip(logs, cut)])
    second = encode_events_columnar(spec.registry,
                                    [l[c:] for l, c in zip(logs, cut)])
    r1 = eng.replay_resident_sharded(eng.prepare_resident_sharded(first))
    r2 = eng.replay_resident_sharded(eng.prepare_resident_sharded(second),
                                     init_carry=r1.states,
                                     ordinal_base=np.asarray(cut, np.int32))
    for i, exp in enumerate(expected):
        assert int(r2.states["count"][i]) == (exp.count if exp else 0), i
        assert int(r2.states["version"][i]) == (exp.version if exp else 0), i


def test_resume_from_snapshot_carry():
    """Replay can resume from checkpointed states (watermark semantics, SURVEY §5.4)."""
    model = counter.CounterModel()
    logs = random_counter_logs(10, 20, seed=11)
    spec = model.replay_spec()
    eng = ReplayEngine(spec)

    # fold first half, decode states, re-encode as carry, fold second half
    half = [l[:len(l) // 2] for l in logs]
    rest = [l[len(l) // 2:] for l in logs]
    res1 = eng.replay_encoded(encode_events(spec.registry, half))
    mid_states = decode_states(spec.registry.state, res1.states)
    carry = eng.carry_from_states(mid_states)
    res2 = eng.replay_encoded(encode_events(spec.registry, rest), init_carry=carry)

    expected = scalar_fold_states(model, logs)
    for i, exp in enumerate(expected):
        assert int(res2.states["count"][i]) == (exp.count if exp else 0)
