"""Mixed aggregate-type replay: three model families folded in ONE batch
(BASELINE.json config "Mixed aggregate-type replay (heterogeneous event
schemas, masked vmap)"). Golden-checked against each model's scalar fold."""

import random

import numpy as np
import pytest

from surge_tpu.config import Config
from surge_tpu.engine.model import fold_events
from surge_tpu.models import bank_account, counter, shopping_cart
from surge_tpu.replay import ReplayEngine
from surge_tpu.replay.mixed import combine_replay_specs
from surge_tpu.testing import (
    random_bank_log as _bank_log,
    random_cart_log as _cart_log,
    random_counter_log as _counter_log,
)


@pytest.mark.parametrize("path", ["columnar", "resident"])
def test_mixed_three_model_families_one_batch(path):
    rng = random.Random(7)
    vocab = bank_account.Vocab()
    cmodel = counter.CounterModel()
    sc_model = shopping_cart.CartModel()
    bmodel = bank_account.BankAccountModel()

    mixed = combine_replay_specs({
        "counter": counter.make_replay_spec(),
        "cart": sc_model.replay_spec(),
        "bank": bmodel.replay_spec(),
    })

    tagged, truths, ids = [], [], []
    for i in range(240):
        kind = i % 3
        agg = f"a{i}"
        if kind == 0:
            log = _counter_log(rng, agg)
            tagged.append(("counter", log))
            truths.append(("counter", fold_events(cmodel, None, log)))
        elif kind == 1:
            log = _cart_log(rng, agg)
            tagged.append(("cart", log))
            truths.append(("cart", fold_events(sc_model, None, log)))
        else:
            log = _bank_log(rng, agg)
            enc = [bank_account.encode_event(vocab, e) for e in log]
            tagged.append(("bank", enc))
            truths.append(("bank", fold_events(bmodel, None, log)))
        ids.append(agg)

    colev = mixed.encode_logs(tagged)
    models = [m for m, _ in tagged]
    eng = ReplayEngine(mixed.spec, config=Config(overrides={
        "surge.replay.batch-size": 64, "surge.replay.time-chunk": 8}))
    init = mixed.init_carry(models)
    if path == "columnar":
        res = eng.replay_columnar(colev, init_carry=init)
    else:
        res = eng.replay_resident(eng.prepare_resident(colev), init_carry=init)
    assert res.num_events == sum(len(l) for _, l in tagged)

    decoded = mixed.decode_states(models, res.states)
    for i, ((kind, truth), got) in enumerate(zip(truths, decoded)):
        if kind == "counter":
            want_count = 0 if truth is None else truth.count
            want_version = 0 if truth is None else truth.version
            assert got.count == want_count, (i, got, truth)
            assert got.version == want_version, (i, got, truth)
        elif kind == "cart":
            want_total = 0 if truth is None else truth.total_cents
            assert got.total_cents == want_total, (i, got, truth)
            assert bool(got.checked_out) == bool(
                truth is not None and truth.checked_out), (i, got, truth)
        else:
            bank_state = bank_account.decode_state(
                vocab, ids[i], bank_account.EncodedAccountState(
                    created=bool(got.created),
                    owner_code=int(got.owner_code),
                    security_code_code=int(got.security_code_code),
                    balance=float(got.balance)))
            if truth is None:
                assert bank_state is None, (i, got)
            else:
                assert bank_state is not None
                assert bank_state.balance == pytest.approx(truth.balance)
                assert bank_state.account_owner == truth.account_owner


def test_mixed_rejects_shared_event_class():
    spec = counter.make_replay_spec()
    with pytest.raises(ValueError):
        combine_replay_specs({"a": spec, "b": spec})
