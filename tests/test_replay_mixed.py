"""Mixed aggregate-type replay: three model families folded in ONE batch
(BASELINE.json config "Mixed aggregate-type replay (heterogeneous event
schemas, masked vmap)"). Golden-checked against each model's scalar fold."""

import random

import numpy as np
import pytest

from surge_tpu.config import Config
from surge_tpu.engine.model import fold_events
from surge_tpu.models import bank_account, counter, shopping_cart
from surge_tpu.replay import ReplayEngine
from surge_tpu.replay.mixed import combine_replay_specs


def _counter_log(rng, agg):
    model = counter.CounterModel()
    state, log = None, []
    for _ in range(rng.randrange(0, 25)):
        cmd = (counter.Increment(agg) if rng.random() < 0.7
               else counter.Decrement(agg))
        for e in model.process_command(state, cmd):
            state = model.handle_event(state, e)
            log.append(e)
    return log


def _cart_log(rng, agg):
    model = shopping_cart.CartModel()
    state, log = None, []
    for _ in range(rng.randrange(0, 20)):
        if state is not None and state.checked_out:
            break
        try:
            r = rng.random()
            if r < 0.6:
                cmd = shopping_cart.AddItem(agg, rng.randrange(1, 50),
                                            rng.randrange(1, 4),
                                            rng.randrange(100, 900))
            elif r < 0.9:
                cmd = shopping_cart.RemoveItem(agg, rng.randrange(1, 50),
                                               rng.randrange(1, 3),
                                               rng.randrange(100, 900))
            else:
                cmd = shopping_cart.Checkout(agg)
            events = model.process_command(state, cmd)
        except Exception:
            continue
        for e in events:
            state = model.handle_event(state, e)
            log.append(e)
    return log


def _bank_log(rng, agg):
    log = []
    if rng.random() < 0.8:
        log.append(bank_account.BankAccountCreated(agg, f"owner{agg}",
                                                   f"sec{agg}", 100.0))
        bal = 100.0
        for _ in range(rng.randrange(0, 12)):
            bal += rng.randrange(1, 40) * 0.25
            log.append(bank_account.BankAccountUpdated(agg, bal))
    else:
        log.append(bank_account.BankAccountUpdated(agg, 42.0))  # orphan
    return log


@pytest.mark.parametrize("path", ["columnar", "resident"])
def test_mixed_three_model_families_one_batch(path):
    rng = random.Random(7)
    vocab = bank_account.Vocab()
    cmodel = counter.CounterModel()
    sc_model = shopping_cart.CartModel()
    bmodel = bank_account.BankAccountModel()

    mixed = combine_replay_specs({
        "counter": counter.make_replay_spec(),
        "cart": sc_model.replay_spec(),
        "bank": bmodel.replay_spec(),
    })

    tagged, truths, ids = [], [], []
    for i in range(240):
        kind = i % 3
        agg = f"a{i}"
        if kind == 0:
            log = _counter_log(rng, agg)
            tagged.append(("counter", log))
            truths.append(("counter", fold_events(cmodel, None, log)))
        elif kind == 1:
            log = _cart_log(rng, agg)
            tagged.append(("cart", log))
            truths.append(("cart", fold_events(sc_model, None, log)))
        else:
            log = _bank_log(rng, agg)
            enc = [bank_account.encode_event(vocab, e) for e in log]
            tagged.append(("bank", enc))
            truths.append(("bank", fold_events(bmodel, None, log)))
        ids.append(agg)

    colev = mixed.encode_logs(tagged)
    models = [m for m, _ in tagged]
    eng = ReplayEngine(mixed.spec, config=Config(overrides={
        "surge.replay.batch-size": 64, "surge.replay.time-chunk": 8}))
    init = mixed.init_carry(models)
    if path == "columnar":
        res = eng.replay_columnar(colev, init_carry=init)
    else:
        res = eng.replay_resident(eng.prepare_resident(colev), init_carry=init)
    assert res.num_events == sum(len(l) for _, l in tagged)

    decoded = mixed.decode_states(models, res.states)
    for i, ((kind, truth), got) in enumerate(zip(truths, decoded)):
        if kind == "counter":
            want_count = 0 if truth is None else truth.count
            want_version = 0 if truth is None else truth.version
            assert got.count == want_count, (i, got, truth)
            assert got.version == want_version, (i, got, truth)
        elif kind == "cart":
            want_total = 0 if truth is None else truth.total_cents
            assert got.total_cents == want_total, (i, got, truth)
            assert bool(got.checked_out) == bool(
                truth is not None and truth.checked_out), (i, got, truth)
        else:
            bank_state = bank_account.decode_state(
                vocab, ids[i], bank_account.EncodedAccountState(
                    created=bool(got.created),
                    owner_code=int(got.owner_code),
                    security_code_code=int(got.security_code_code),
                    balance=float(got.balance)))
            if truth is None:
                assert bank_state is None, (i, got)
            else:
                assert bank_state is not None
                assert bank_state.balance == pytest.approx(truth.balance)
                assert bank_state.account_owner == truth.account_owner


def test_mixed_rejects_shared_event_class():
    spec = counter.make_replay_spec()
    with pytest.raises(ValueError):
        combine_replay_specs({"a": spec, "b": spec})
