"""Per-stage replay profiler: stage coverage on the streaming and resident
paths, span emission, DEBUG gating (disabled at INFO = engine holds None)."""

import numpy as np

from surge_tpu.codec.tensor import ColumnarEvents
from surge_tpu.config import default_config
from surge_tpu.metrics import Metrics, RecordingLevel, engine_metrics
from surge_tpu.models.counter import make_replay_spec
from surge_tpu.replay.engine import ReplayEngine
from surge_tpu.replay.profiler import ReplayProfiler
from surge_tpu.tracing import InMemoryTracer

CFG = default_config().with_overrides({
    "surge.replay.batch-size": 64,
    "surge.replay.time-chunk": 16,
})


def make_events(n_agg=32, n_per=20):
    n = n_agg * n_per
    return ColumnarEvents(
        num_aggregates=n_agg,
        agg_idx=np.repeat(np.arange(n_agg, dtype=np.int32), n_per),
        type_ids=np.zeros(n, dtype=np.int32),
        cols={"increment_by": np.ones(n, dtype=np.int64),
              "decrement_by": np.zeros(n, dtype=np.int64)},
        derived_cols={"sequence_number": "ordinal"})


def make_profiled_engine(tracer=None):
    registry = Metrics(recording_level=RecordingLevel.DEBUG)
    prof = ReplayProfiler.if_enabled(registry, engine_metrics(registry),
                                     tracer=tracer)
    assert prof is not None
    return ReplayEngine(make_replay_spec(), config=CFG, profiler=prof), prof, registry


def test_if_enabled_gates_on_recording_level():
    assert ReplayProfiler.if_enabled(Metrics()) is None  # INFO: hot path free
    assert ReplayProfiler.if_enabled(
        Metrics(recording_level=RecordingLevel.DEBUG)) is not None
    assert ReplayProfiler.if_enabled(
        Metrics(recording_level=RecordingLevel.TRACE)) is not None


def test_streaming_path_stage_breakdown():
    engine, prof, registry = make_profiled_engine()
    ev = make_events()
    res = engine.replay_columnar(ev)
    assert (res.states["count"] == 20).all()
    s = prof.summary()
    # windowed path: pack + transfer + (first-dispatch) compile + fetch
    assert s["encode"]["count"] > 0
    assert s["h2d"]["count"] > 0
    assert s["compile"]["count"] > 0  # first window paid the XLA compile
    assert s["fetch"]["count"] > 0
    assert s["total_accounted_s"] > 0
    # windows counts DISPATCHED windows (engine-reported), not record() calls
    assert s["windows"] == engine.stats["windows"]
    # the per-stage timings also landed in the DEBUG registry instruments
    snap = registry.get_metrics()
    assert snap["surge.replay.profile.windows"] == engine.stats["windows"]
    assert snap["surge.replay.profile.compile-timer.max"] > 0
    # a re-fold of the same shapes is steady: dispatch, not compile
    before = s["compile"]["count"]
    engine.replay_columnar(ev)
    s2 = prof.summary()
    assert s2["compile"]["count"] == before
    assert s2["dispatch"]["count"] > 0


def test_resident_path_emits_pass_and_stage_spans():
    tracer = InMemoryTracer()
    engine, prof, _ = make_profiled_engine(tracer=tracer)
    ev = make_events()
    resident = engine.prepare_resident(ev)
    res = engine.replay_resident(resident)
    assert (res.states["count"] == 20).all()
    s = prof.summary()
    assert s["encode"]["count"] > 0  # pack_resident
    assert s["h2d"]["count"] > 0  # upload_resident
    assert s["fetch"]["count"] > 0  # the single state pull
    names = [sp.name for sp in tracer.finished]
    assert "replay.resident" in names
    assert "replay.fetch" in names
    # stage spans parent under the pass span, one trace per pass
    pass_span = tracer.spans_named("replay.resident")[0]
    fetch = tracer.spans_named("replay.fetch")[0]
    assert fetch.context.trace_id == pass_span.context.trace_id
    assert fetch.parent_id == pass_span.context.span_id
    assert pass_span.attributes["events"] == ev.num_events


def test_unprofiled_engine_holds_none_and_matches_results():
    plain = ReplayEngine(make_replay_spec(), config=CFG)
    assert plain.profiler is None
    engine, _, _ = make_profiled_engine()
    ev = make_events()
    a = plain.replay_columnar(ev)
    b = engine.replay_columnar(ev)
    assert (a.states["count"] == b.states["count"]).all()
    assert (a.states["version"] == b.states["version"]).all()


def test_summary_reset():
    engine, prof, _ = make_profiled_engine()
    engine.replay_columnar(make_events(8, 4))
    assert prof.summary()["total_accounted_s"] > 0
    prof.reset()
    s = prof.summary()
    assert s["total_accounted_s"] == 0
    assert all(s[k]["count"] == 0 for k in
               ("encode", "h2d", "compile", "dispatch", "fetch"))


def test_refresh_stage_covers_incremental_folds():
    """The resident plane's incremental folds land in the per-stage profile
    like cold-start passes: `refresh` is the per-round umbrella, its host
    pack shows under `encode`, the first window under `compile` and repeats
    under `dispatch`."""
    import asyncio

    from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
    from surge_tpu.models.counter import (CountIncremented, event_formatting,
                                          state_formatting)
    from surge_tpu.replay.profiler import ReplayProfiler
    from surge_tpu.replay.resident_state import ResidentStatePlane
    from surge_tpu.serialization import SerializedMessage

    registry = Metrics(recording_level=RecordingLevel.DEBUG)
    prof = ReplayProfiler.if_enabled(registry, engine_metrics(registry))
    evt, st = event_formatting(), state_formatting()
    log = InMemoryLog()
    log.create_topic(TopicSpec("events", 1))

    def append(events):
        prod = log.transactional_producer("t")
        prod.begin()
        for ev in events:
            msg = evt.write_event(ev)
            prod.send(LogRecord(topic="events", partition=0,
                                key=msg.key, value=msg.value))
        prod.commit()

    async def scenario():
        plane = ResidentStatePlane(
            log, "events", make_replay_spec(),
            config=default_config().with_overrides({
                "surge.replay.batch-size": 16, "surge.replay.time-chunk": 8,
                "surge.replay.resident.refresh-interval-ms": 5}),
            deserialize_event=lambda raw: evt.read_event(
                SerializedMessage(key="", value=raw)),
            serialize_state=lambda a, s: st.write_state(s).value,
            profiler=prof)
        await plane.start()
        try:
            append([CountIncremented(f"a{i}", 1, 1) for i in range(8)])
            for _ in range(200):
                if plane.lag_records() == 0 and plane.stats["rounds"] > 0:
                    break
                await asyncio.sleep(0.01)
            append([CountIncremented(f"a{i}", 1, 2) for i in range(8)])
            for _ in range(200):
                if plane.lag_records() == 0 and plane.stats["rounds"] > 1:
                    break
                await asyncio.sleep(0.01)
        finally:
            await plane.stop()
        return plane

    plane = asyncio.run(scenario())
    s = prof.summary()
    assert s["refresh"]["count"] == plane.stats["rounds"] >= 2
    assert s["encode"]["count"] >= s["refresh"]["count"]
    assert s["compile"]["count"] > 0   # first refresh window paid the compile
    assert s["dispatch"]["count"] > 0  # the repeat round reused the program
    snap = registry.get_metrics()
    assert snap["surge.replay.profile.refresh-timer.max"] > 0
