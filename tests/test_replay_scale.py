"""Scale-discipline tests: B-chunking, fixed-width streaming, donation safety,
columnar encode — the VERDICT r1 "weak" items around HBM budget and compile count."""

import numpy as np
import pytest

from surge_tpu.codec import encode_events
from surge_tpu.codec.tensor import (
    ColumnarEvents,
    columnar_to_batch,
    encode_events_columnar,
)
from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import fold_events
from surge_tpu.models import counter
from surge_tpu.replay import ReplayEngine

from tests.test_replay_golden import random_counter_logs, scalar_fold_states


def test_columnar_encode_matches_object_encode():
    logs = random_counter_logs(23, 17, seed=21)
    reg = counter.make_registry()
    enc_obj = encode_events(reg, logs)
    colev = encode_events_columnar(reg, logs)
    enc_col = columnar_to_batch(colev)
    np.testing.assert_array_equal(enc_obj.type_ids, enc_col.type_ids)
    np.testing.assert_array_equal(enc_obj.lengths, enc_col.lengths)
    for name in enc_obj.cols:
        np.testing.assert_array_equal(enc_obj.cols[name], enc_col.cols[name])


def test_columnar_scatter_pure_numpy_path():
    """Synthetic columnar log (no Python objects at all) folds correctly."""
    rng = np.random.default_rng(0)
    b, n = 50, 4000
    agg_idx = np.sort(rng.integers(0, b, size=n).astype(np.int32))
    type_ids = rng.integers(0, 2, size=n).astype(np.int32)  # inc / dec
    inc = np.where(type_ids == 0, rng.integers(1, 4, size=n), 0).astype(np.int32)
    dec = np.where(type_ids == 1, rng.integers(1, 4, size=n), 0).astype(np.int32)
    seq = np.ones(n, dtype=np.int32)
    colev = ColumnarEvents(num_aggregates=b, agg_idx=agg_idx, type_ids=type_ids,
                           cols={"increment_by": inc, "decrement_by": dec,
                                 "sequence_number": seq})
    eng = ReplayEngine(counter.make_replay_spec())
    res = eng.replay_columnar(colev)
    # ground truth via numpy segment sums
    expected = (np.bincount(agg_idx, weights=inc, minlength=b)
                - np.bincount(agg_idx, weights=dec, minlength=b))
    np.testing.assert_array_equal(res.states["count"], expected.astype(np.int32))
    assert res.num_events == n


def test_b_chunking_bounds_device_batch():
    """batch-size smaller than B: replay must chunk and still be exact."""
    model = counter.CounterModel()
    logs = random_counter_logs(100, 15, seed=22)
    expected = scalar_fold_states(model, logs)
    cfg = Config(overrides={"surge.replay.batch-size": 16, "surge.replay.time-chunk": 8})
    eng = ReplayEngine(model.replay_spec(), config=cfg)
    assert eng.batch_size == 16  # lane multiple of 8 on single device
    res = eng.replay_encoded(encode_events(model.replay_spec().registry, logs))
    for i, exp in enumerate(expected):
        assert int(res.states["count"][i]) == (exp.count if exp else 0)
        assert int(res.states["version"][i]) == (exp.version if exp else 0)
    # one compiled program serves all (B-chunk, T-chunk) windows
    assert eng.num_compiles() == 1


def test_stream_compiled_programs_bounded_by_ladder():
    """Varying-width stream chunks must not compile per input width: padded widths
    come from the fixed time-chunk + power-of-two tail ladder, so the program
    count is bounded by ``1 + log2(chunk/min-time-window)`` no matter how many
    distinct widths arrive."""
    model = counter.CounterModel()
    logs = random_counter_logs(8, 33, seed=23)
    spec = model.replay_spec()
    cfg = Config(overrides={"surge.replay.time-chunk": 16})
    eng = ReplayEngine(spec, config=cfg)

    def chunks():
        t = max(len(l) for l in logs)
        # deliberately ragged window widths: 13, then 7s
        bounds = [0, 13]
        while bounds[-1] < t:
            bounds.append(min(bounds[-1] + 7, t))
        for s, e in zip(bounds, bounds[1:]):
            yield encode_events(spec.registry, [l[s:e] for l in logs], pad_to=e - s)

    res = eng.replay_stream(chunks(), batch=len(logs))
    expected = scalar_fold_states(model, logs)
    for i, exp in enumerate(expected):
        assert int(res.states["count"][i]) == (exp.count if exp else 0)
    # widths 13 and 7 map onto ladder programs {16, 8}, never one per width
    assert eng.num_compiles() <= 2

    # with the ladder disabled every window pads to the full time-chunk: exactly
    # one program regardless of input widths (the round-3 contract)
    eng2 = ReplayEngine(spec, config=Config(overrides={
        "surge.replay.time-chunk": 16, "surge.replay.min-time-window": 0}))
    res2 = eng2.replay_stream(chunks(), batch=len(logs))
    for i, exp in enumerate(expected):
        assert int(res2.states["count"][i]) == (exp.count if exp else 0)
    assert eng2.num_compiles() == 1


def test_external_carry_not_donated():
    """ADVICE r1 (medium): caller-supplied init_carry must survive the fold, even when
    batch is exactly lane-aligned (no padding copy)."""
    model = counter.CounterModel()
    spec = model.replay_spec()
    eng = ReplayEngine(spec)
    b = 8  # exactly the lane multiple: the r1 bug path
    logs = random_counter_logs(b, 10, seed=24)
    enc = encode_events(spec.registry, logs)
    carry = {"count": np.full(b, 5, dtype=np.int32),
             "version": np.zeros(b, dtype=np.int32)}
    res1 = eng.replay_encoded(enc, init_carry=carry)
    # reuse the same carry — r1 raised "Buffer has been deleted or donated" here
    res2 = eng.replay_encoded(enc, init_carry=carry)
    np.testing.assert_array_equal(res1.states["count"], res2.states["count"])
    np.testing.assert_array_equal(np.asarray(carry["count"]), np.full(b, 5))


def test_out_of_range_type_id_is_padding():
    """ADVICE r1: corrupt positive type_ids must carry state through, not dispatch."""
    spec = counter.make_replay_spec()
    eng = ReplayEngine(spec)
    b = 8
    colev = ColumnarEvents(
        num_aggregates=b,
        agg_idx=np.repeat(np.arange(b, dtype=np.int32), 2),
        type_ids=np.tile(np.array([0, 99], dtype=np.int32), b),  # inc, then corrupt
        cols={"increment_by": np.ones(2 * b, dtype=np.int32),
              "decrement_by": np.zeros(2 * b, dtype=np.int32),
              "sequence_number": np.ones(2 * b, dtype=np.int32)})
    res = eng.replay_columnar(colev)
    np.testing.assert_array_equal(res.states["count"], np.ones(b, dtype=np.int32))


def test_unserializable_event_tensor_parity():
    """ADVICE r1: UnserializableEvent folds on the tensor path (version bump)."""
    model = counter.CounterModel()
    logs = [[counter.CountIncremented("0", 2, 1),
             counter.UnserializableEvent("0", 2, "boom")]]
    expected = scalar_fold_states(model, logs)[0]
    eng = ReplayEngine(model.replay_spec())
    res = eng.replay_encoded(encode_events(model.replay_spec().registry, logs))
    assert int(res.states["count"][0]) == expected.count == 2
    assert int(res.states["version"][0]) == expected.version == 2


def test_config_with_overrides_kwargs():
    """ADVICE r1: kwarg overrides must canonicalize to dotted/dashed keys."""
    cfg = default_config().with_overrides(surge_replay_time_chunk=99)
    assert cfg.get_int("surge.replay.time-chunk") == 99
    cfg2 = default_config().with_overrides({"surge.replay.batch-size": 7})
    assert cfg2.get_int("surge.replay.batch-size") == 7


def test_columnar_chunked_skewed_lengths():
    """replay_columnar densifies per B-chunk: one huge log must not blow up padding
    for other chunks (bounded host memory)."""
    rng = np.random.default_rng(3)
    b = 40
    parts = []
    for i in range(b):
        ln = 500 if i == 0 else int(rng.integers(1, 10))
        parts.append(np.full(ln, i, dtype=np.int32))
    agg_idx = np.concatenate(parts)
    n = agg_idx.size
    type_ids = rng.integers(0, 2, size=n).astype(np.int32)
    inc = np.where(type_ids == 0, 1, 0).astype(np.int32)
    dec = np.where(type_ids == 1, 1, 0).astype(np.int32)
    colev = ColumnarEvents(b, agg_idx, type_ids,
                           {"increment_by": inc, "decrement_by": dec,
                            "sequence_number": np.ones(n, dtype=np.int32)})
    cfg = Config(overrides={"surge.replay.batch-size": 8, "surge.replay.time-chunk": 32})
    eng = ReplayEngine(counter.make_replay_spec(), config=cfg)
    res = eng.replay_columnar(colev)
    expected = (np.bincount(agg_idx, weights=inc, minlength=b)
                - np.bincount(agg_idx, weights=dec, minlength=b)).astype(np.int32)
    np.testing.assert_array_equal(res.states["count"], expected)
    # the 500-long log only inflates its own chunk: padding ≤ chunk0(512*8) + others(32*8 each)
    assert res.padded_events <= 8 * 512 + (b // 8 - 1) * 8 * 32 + 8 * 32


def test_length_sorted_chunking_cuts_padding_and_stays_exact():
    """VERDICT r3 next #2: with a skewed length distribution, length-sorted
    B-chunking plus the tail-window ladder must bring pad_ratio near 1 while
    producing byte-identical states in the caller's original aggregate order."""
    rng = np.random.default_rng(7)
    b = 256
    # heavy skew: most logs short, a few long — the distribution that produced
    # pad_ratio 6.29 unsorted at bench scale
    lens = np.where(rng.random(b) < 0.9,
                    rng.integers(1, 12, size=b),
                    rng.integers(200, 400, size=b)).astype(np.int64)
    order = rng.permutation(b)  # lengths deliberately interleaved
    lens = lens[order]
    parts = [np.full(lens[i], i, dtype=np.int32) for i in range(b)]
    agg_idx = np.concatenate(parts)
    n = agg_idx.size
    type_ids = rng.integers(0, 2, size=n).astype(np.int32)
    inc = np.where(type_ids == 0, rng.integers(1, 4, size=n), 0).astype(np.int32)
    dec = np.where(type_ids == 1, 1, 0).astype(np.int32)
    cols = {"increment_by": inc, "decrement_by": dec,
            "sequence_number": np.ones(n, dtype=np.int32)}
    expected = (np.bincount(agg_idx, weights=inc, minlength=b)
                - np.bincount(agg_idx, weights=dec, minlength=b)).astype(np.int32)

    cfg = Config(overrides={"surge.replay.batch-size": 32,
                            "surge.replay.time-chunk": 64})
    eng = ReplayEngine(counter.make_replay_spec(), config=cfg)
    res = eng.replay_columnar(ColumnarEvents(b, agg_idx, type_ids, dict(cols)))
    np.testing.assert_array_equal(res.states["count"], expected)
    ratio_sorted = res.padded_events / n

    off = Config(overrides={"surge.replay.batch-size": 32,
                            "surge.replay.time-chunk": 64,
                            "surge.replay.sort-by-length": False,
                            "surge.replay.min-time-window": 0})
    eng_off = ReplayEngine(counter.make_replay_spec(), config=off)
    res_off = eng_off.replay_columnar(ColumnarEvents(b, agg_idx, type_ids, dict(cols)))
    np.testing.assert_array_equal(res_off.states["count"], expected)
    ratio_unsorted = res_off.padded_events / n

    assert ratio_sorted < ratio_unsorted / 2  # the lever actually levers
    assert ratio_sorted < 2.0


def test_resident_corpus_replay_matches_streaming_and_scalar():
    """Resident-corpus replay (one flat upload + on-device gather densify) must
    produce byte-identical states to the streaming window path and the scalar
    fold, in the caller's original aggregate order, while shipping exactly
    wire_bytes_per_event() per event."""
    from surge_tpu.replay.corpus import synth_counter_corpus

    corpus = synth_counter_corpus(3000, 120_000, seed=17)  # unsorted order
    cfg = Config(overrides={"surge.replay.batch-size": 256,
                            "surge.replay.time-chunk": 32,
                            "surge.replay.resident-len-bucket": "exact"})
    eng = ReplayEngine(counter.make_replay_spec(), config=cfg)
    resident = eng.prepare_resident(corpus.events)
    # 1 byte/event on the link + the guard tail (slice safety); exact bucket
    # policy so the shipped bytes equal the information bytes
    from surge_tpu.replay.engine import _WIRE_GUARD_MIN
    guard = max(eng.resident_tile_width(), _WIRE_GUARD_MIN)
    assert resident.wire_bytes == corpus.num_events + guard
    res = eng.replay_resident(resident)
    np.testing.assert_array_equal(res.states["count"], corpus.expected_count)
    np.testing.assert_array_equal(res.states["version"], corpus.expected_version)
    assert res.num_events == corpus.num_events

    # streaming path agreement (same engine, same config)
    res2 = eng.replay_columnar(corpus.events)
    for name in res.states:
        np.testing.assert_array_equal(res.states[name], res2.states[name])


def test_resident_plan_small_tile_divides_big():
    """bs_small must divide bs_big whatever the batch-size knob says: the
    narrow-tile walk steps in bs_small over a buffer padded only to a bs_big
    multiple, so a non-divisor's clamped last tile would silently re-apply a
    round's events to already-covered lanes (ADVICE r4). The awkward
    batch-sizes here exercise the guard AND the replay must stay exact."""
    from surge_tpu.replay.corpus import synth_counter_corpus

    corpus = synth_counter_corpus(1500, 60_000, seed=23)
    for batch in (1007, 72):
        cfg = Config(overrides={"surge.replay.batch-size": batch,
                                "surge.replay.time-chunk": 32,
                                "surge.replay.resident-len-bucket": "exact"})
        eng = ReplayEngine(counter.make_replay_spec(), config=cfg)
        resident = eng.prepare_resident(corpus.events)
        plan = eng._resident_plan(resident)
        assert plan.bs_big % plan.bs_small == 0, (batch, plan)
        if plan.small_i0.size:
            # every narrow tile stays inside the padded lane buffer unclamped
            assert int(plan.small_i0.max()) + plan.bs_small <= resident.b_pad
        res = eng.replay_resident(resident)
        np.testing.assert_array_equal(res.states["count"], corpus.expected_count)
        np.testing.assert_array_equal(res.states["version"],
                                      corpus.expected_version)


def test_resident_wire_save_load_roundtrip(tmp_path):
    """pack_resident -> save -> mmap load -> upload must replay identically to
    the direct prepare_resident path (the cold-start-from-segment flow)."""
    from surge_tpu.replay.corpus import synth_counter_corpus
    from surge_tpu.replay.engine import ResidentWire

    corpus = synth_counter_corpus(800, 40_000, seed=9)
    cfg = Config(overrides={"surge.replay.batch-size": 128,
                            "surge.replay.time-chunk": 32})
    eng = ReplayEngine(counter.make_replay_spec(), config=cfg)
    wire = eng.pack_resident(corpus.events)
    wire.save(str(tmp_path / "wire"))
    loaded = ResidentWire.load(str(tmp_path / "wire"))
    res = eng.replay_resident(eng.upload_resident(loaded))
    np.testing.assert_array_equal(res.states["count"], corpus.expected_count)
    np.testing.assert_array_equal(res.states["version"], corpus.expected_version)

    # an engine whose tile width exceeds the packed guard must refuse the wire
    # (its slab slices could read past the buffer)
    big = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
        "surge.replay.batch-size": 256,
        "surge.replay.time-chunk": 32768,
        "surge.replay.resident-slab-cap-mb": 100000}))
    assert big.resident_tile_width() > loaded.guard
    with pytest.raises(ValueError):
        big.upload_resident(loaded)


def test_streamed_resident_replay_matches_plain():
    """replay_resident_streamed (piecewise upload+dispatch, one sync pass)
    must equal the plain resident replay and the closed form, including
    resume, across awkward segment counts."""
    from surge_tpu.replay.corpus import synth_counter_corpus

    corpus = synth_counter_corpus(3100, 130_000, seed=19)
    eng = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
        "surge.replay.batch-size": 256, "surge.replay.time-chunk": 32}))
    wire = eng.pack_resident(corpus.events)
    plain = eng.replay_resident(eng.upload_resident(wire))
    for segments in (2, 3, 7):
        streamed = eng.replay_resident_streamed(wire, segments=segments)
        for name in plain.states:
            np.testing.assert_array_equal(streamed.states[name],
                                          plain.states[name],
                                          err_msg=f"segments={segments}")
    np.testing.assert_array_equal(plain.states["count"], corpus.expected_count)

    # resume mid-log through the streamed path
    ev = corpus.events
    n = ev.num_events
    half_mask = np.arange(n) < n // 2
    import dataclasses

    def subset(mask):
        return dataclasses.replace(
            ev, agg_idx=ev.agg_idx[mask], type_ids=ev.type_ids[mask],
            cols={k: v[mask] for k, v in ev.cols.items()})

    first = eng.pack_resident(subset(half_mask))
    second = eng.pack_resident(subset(~half_mask))
    r1 = eng.replay_resident_streamed(first, segments=3)
    counts1 = np.bincount(ev.agg_idx[half_mask], minlength=ev.num_aggregates)
    r2 = eng.replay_resident_streamed(second, segments=3,
                                      init_carry=r1.states,
                                      ordinal_base=counts1.astype(np.int32))
    np.testing.assert_array_equal(r2.states["count"], corpus.expected_count)
    np.testing.assert_array_equal(r2.states["version"], corpus.expected_version)

    # segments=1 degrades to the plain path
    one = eng.replay_resident_streamed(wire, segments=1)
    for name in plain.states:
        np.testing.assert_array_equal(one.states[name], plain.states[name])


def test_chunked_upload_reassembles_exactly():
    """_chunked_put must round-trip arbitrary arrays byte-exactly (it carries
    the wire bytes the fold decodes) and the chunked replay must match the
    single-put replay."""
    from surge_tpu.replay.corpus import synth_counter_corpus
    from surge_tpu.replay.engine import _chunked_put

    rng = np.random.default_rng(3)
    for shape in ((1_500_000, 1), (1_234_567,), (3, 5)):
        a = rng.integers(0, 255, size=shape).astype(np.uint8)
        np.testing.assert_array_equal(np.asarray(_chunked_put(a, 1)), a)

    corpus = synth_counter_corpus(2000, 150_000, seed=14)
    outs = {}
    for mb in (0, 1):
        eng = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
            "surge.replay.batch-size": 256,
            "surge.replay.upload-chunk-mb": mb}))
        outs[mb] = eng.replay_resident(eng.prepare_resident(corpus.events))
    for name in outs[0].states:
        np.testing.assert_array_equal(outs[0].states[name], outs[1].states[name])
    np.testing.assert_array_equal(outs[1].states["count"], corpus.expected_count)


def test_pallas_tile_backend_matches_xla():
    """surge.replay.tile-backend=pallas must fold byte-identically to the XLA
    scan (interpret mode on CPU runs the same kernel program), across models
    with packed-only (counter) and float-side (bank_account) wires."""
    import random

    from surge_tpu.codec.tensor import encode_events_columnar
    from surge_tpu.models import bank_account as ba
    from surge_tpu.replay.corpus import synth_counter_corpus

    corpus = synth_counter_corpus(900, 45_000, seed=8)
    outs = {}
    for backend in ("xla", "pallas"):
        eng = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
            "surge.replay.batch-size": 256, "surge.replay.time-chunk": 32,
            "surge.replay.tile-backend": backend}))
        outs[backend] = eng.replay_resident(eng.prepare_resident(corpus.events))
    for name in outs["xla"].states:
        np.testing.assert_array_equal(outs["xla"].states[name],
                                      outs["pallas"].states[name])
    np.testing.assert_array_equal(outs["pallas"].states["count"],
                                  corpus.expected_count)

    rng = random.Random(2)
    vocab = ba.Vocab()
    enc_logs = []
    for i in range(130):
        log = [ba.BankAccountCreated(str(i), f"o{i}", "s", 100.0)]
        bal = 100.0
        for _ in range(rng.randrange(0, 9)):
            bal += rng.randrange(1, 20) * 0.25
            log.append(ba.BankAccountUpdated(str(i), bal))
        enc_logs.append([ba.encode_event(vocab, e) for e in log])
    bspec = ba.BankAccountModel().replay_spec()
    bcolev = encode_events_columnar(bspec.registry, enc_logs)
    bouts = {}
    for backend in ("xla", "pallas"):
        eng = ReplayEngine(bspec, config=Config(overrides={
            "surge.replay.batch-size": 64, "surge.replay.time-chunk": 8,
            "surge.replay.tile-backend": backend}))
        bouts[backend] = eng.replay_resident(eng.prepare_resident(bcolev))
    for name in bouts["xla"].states:
        np.testing.assert_array_equal(bouts["xla"].states[name],
                                      bouts["pallas"].states[name])


def test_select_dispatch_matches_switch_dispatch():
    """The branchless select lowering must be state-identical to lax.switch
    across the resident and streaming paths (it exists purely as a VPU-friendly
    lowering choice, surge.replay.dispatch)."""
    from surge_tpu.replay.corpus import synth_counter_corpus

    corpus = synth_counter_corpus(1200, 60_000, seed=23)
    results = {}
    for dispatch in ("switch", "select"):
        eng = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
            "surge.replay.batch-size": 256, "surge.replay.time-chunk": 32,
            "surge.replay.dispatch": dispatch}))
        r1 = eng.replay_resident(eng.prepare_resident(corpus.events))
        r2 = eng.replay_columnar(corpus.events)
        for name in r1.states:
            np.testing.assert_array_equal(r1.states[name], r2.states[name])
        results[dispatch] = r1
    for name in results["switch"].states:
        np.testing.assert_array_equal(results["switch"].states[name],
                                      results["select"].states[name])
    np.testing.assert_array_equal(results["select"].states["count"],
                                  corpus.expected_count)


def test_resident_len_bucketing_reuses_programs_across_sizes():
    """With the default pow2 length bucketing, replaying two different-sized
    corpora (e.g. consecutive restore chunks) whose buffers land in the same
    bucket must not add a second compiled-program signature."""
    from surge_tpu.replay.corpus import synth_counter_corpus

    eng = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
        "surge.replay.batch-size": 128, "surge.replay.time-chunk": 32}))
    c1 = synth_counter_corpus(500, 20_000, seed=1)
    c2 = synth_counter_corpus(470, 23_000, seed=2)
    r1 = eng.replay_resident(eng.prepare_resident(c1.events))
    n_after_first = eng.num_compiles()
    r2 = eng.replay_resident(eng.prepare_resident(c2.events))
    assert eng.num_compiles() == n_after_first, "same bucket must reuse programs"
    np.testing.assert_array_equal(r1.states["count"], c1.expected_count)
    np.testing.assert_array_equal(r2.states["count"], c2.expected_count)


def test_resident_wire_layout_mismatch_refused(tmp_path):
    """A wire packed under a different schema layout must be refused at upload
    (silent misaligned decode would fold wrong states)."""
    import dataclasses

    from surge_tpu.models import bank_account as ba
    from surge_tpu.replay.corpus import synth_counter_corpus
    from surge_tpu.replay.engine import ResidentWire

    corpus = synth_counter_corpus(100, 2_000, seed=4)
    eng = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
        "surge.replay.batch-size": 64}))
    wire = eng.pack_resident(corpus.events)
    # forge a layout drift: pretend the wire was packed with 2 bytes/event
    forged = dataclasses.replace(
        wire, packed=np.repeat(wire.packed, 2, axis=1))
    with pytest.raises(ValueError, match="layout mismatch"):
        eng.upload_resident(forged)
    # same byte count but different BIT layout (field shifts moved) must also
    # be refused — the fingerprint pins positions, not just widths
    drifted_layout = dict(wire.layout)
    drifted_layout["packed"] = [[n, d, b, s + 1]
                                for n, d, b, s in drifted_layout["packed"]]
    with pytest.raises(ValueError, match="layout mismatch"):
        eng.upload_resident(dataclasses.replace(wire, layout=drifted_layout))
    # and a different model's engine must refuse this wire's side columns
    beng = ReplayEngine(ba.BankAccountModel().replay_spec(),
                        config=Config(overrides={"surge.replay.batch-size": 64}))
    with pytest.raises(ValueError):
        beng.upload_resident(wire)


def test_resident_unsorted_skewed_plan_stays_chunk_local():
    """With sort-by-length disabled and one lane's log dwarfing the rest, the
    tile plan must stay bounded by each lane range's LOCAL max (the streaming
    path's bound), not schedule every range out to the global max."""
    from surge_tpu.codec.tensor import ColumnarEvents
    from surge_tpu.replay.corpus import synth_counter_corpus

    corpus = synth_counter_corpus(600, 6_000, seed=3)
    # graft a long tail onto ONE aggregate: 4000 extra increments on agg 7
    ev = corpus.events
    extra = 4000
    agg_idx = np.concatenate([ev.agg_idx, np.full(extra, 7, dtype=ev.agg_idx.dtype)])
    type_ids = np.concatenate([ev.type_ids, np.zeros(extra, dtype=ev.type_ids.dtype)])
    cols = {k: np.concatenate([v, np.ones(extra, dtype=v.dtype) if k == "increment_by"
                               else np.zeros(extra, dtype=v.dtype)])
            for k, v in ev.cols.items()}
    colev = ColumnarEvents(num_aggregates=600, agg_idx=agg_idx, type_ids=type_ids,
                           cols=cols, derived_cols=dict(ev.derived_cols))
    cfg = Config(overrides={"surge.replay.batch-size": 128,
                            "surge.replay.time-chunk": 32,
                            "surge.replay.sort-by-length": False})
    eng = ReplayEngine(counter.make_replay_spec(), config=cfg)
    resident = eng.prepare_resident(colev)
    plan = eng._resident_plan(resident)
    # only aggregate 7's range pays for the long log; the others stop at their
    # local max (~tens of events), so the slot bound is far below b×max_len
    assert plan.padded_slots < 600 * 4000 // 2
    res = eng.replay_resident(resident)
    scalar = eng.replay_columnar(colev)
    for name in res.states:
        np.testing.assert_array_equal(res.states[name], scalar.states[name])


def test_resident_replay_with_side_columns_and_resume():
    """bank_account has float side columns (they ride the flat side arrays);
    resume through init_carry/ordinal_base must continue derived ordinals."""
    from surge_tpu.models import bank_account as ba

    rng = np.random.default_rng(3)
    reg = ba.make_registry()
    logs = []
    for i in range(60):
        n = int(rng.integers(1, 12))
        evs = [ba.EncodedCreated(owner_code=i % 5, security_code_code=1,
                                 balance=np.float32(100.0))]
        for k in range(n):
            evs.append(ba.EncodedUpdated(new_balance=np.float32(
                100.0 + (k + 1) * 0.25)))
        logs.append(evs)
    colev = encode_events_columnar(reg, logs)
    cfg = Config(overrides={"surge.replay.batch-size": 16,
                            "surge.replay.time-chunk": 8})
    eng = ReplayEngine(ba.make_replay_spec(), config=cfg)
    resident = eng.prepare_resident(colev)
    res = eng.replay_resident(resident)
    ref = eng.replay_columnar(colev)
    for name in res.states:
        np.testing.assert_array_equal(res.states[name], ref.states[name])

    # split replay: fold first half of every log, then resume on the second
    from surge_tpu.replay.corpus import synth_counter_corpus

    corpus = synth_counter_corpus(64, 4000, seed=11)
    ev = corpus.events
    starts = np.zeros(corpus.num_aggregates + 1, dtype=np.int64)
    np.cumsum(corpus.lengths, out=starts[1:])
    first_len = corpus.lengths // 2
    keep = np.zeros(corpus.num_events, dtype=bool)
    for b in range(corpus.num_aggregates):
        keep[starts[b]: starts[b] + first_len[b]] = True

    def subset(mask):
        return ColumnarEvents(
            num_aggregates=corpus.num_aggregates, agg_idx=ev.agg_idx[mask],
            type_ids=ev.type_ids[mask],
            cols={k: v[mask] for k, v in ev.cols.items()},
            derived_cols=dict(ev.derived_cols))

    ceng = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
        "surge.replay.batch-size": 32, "surge.replay.time-chunk": 16}))
    r1 = ceng.replay_resident(ceng.prepare_resident(subset(keep)))
    r2 = ceng.replay_resident(
        ceng.prepare_resident(subset(~keep)),
        init_carry=r1.states,
        ordinal_base=first_len.astype(np.int32))
    np.testing.assert_array_equal(r2.states["count"], corpus.expected_count)
    np.testing.assert_array_equal(r2.states["version"], corpus.expected_version)


def test_resume_with_derived_ordinals_continues_sequence():
    """Checkpoint-resume over a derived-ordinal corpus: the second half's derived
    sequence numbers must continue from each aggregate's already-folded count
    (ordinal_base), not restart at 1 (which would corrupt version)."""
    import numpy as np

    from surge_tpu.models.counter import make_replay_spec
    from surge_tpu.replay.corpus import synth_counter_corpus
    from surge_tpu.replay.engine import ReplayEngine

    corpus = synth_counter_corpus(64, 4000, seed=11)
    ev = corpus.events  # aggregate-sorted flat columnar stream
    engine = ReplayEngine(make_replay_spec())

    # split each aggregate's log in half at the event level
    starts = np.zeros(corpus.num_aggregates + 1, dtype=np.int64)
    np.cumsum(corpus.lengths, out=starts[1:])
    first_len = corpus.lengths // 2
    keep_first = np.zeros(corpus.num_events, dtype=bool)
    for b in range(corpus.num_aggregates):
        keep_first[starts[b]: starts[b] + first_len[b]] = True

    from surge_tpu.codec.tensor import ColumnarEvents

    def subset(mask):
        return ColumnarEvents(
            num_aggregates=corpus.num_aggregates, agg_idx=ev.agg_idx[mask],
            type_ids=ev.type_ids[mask],
            cols={k: v[mask] for k, v in ev.cols.items()},
            derived_cols=dict(ev.derived_cols))

    r1 = engine.replay_columnar(subset(keep_first))
    r2 = engine.replay_columnar(subset(~keep_first), init_carry=r1.states,
                                ordinal_base=first_len.astype(np.int32))
    assert np.array_equal(r2.states["count"], corpus.expected_count)
    assert np.array_equal(r2.states["version"], corpus.expected_version)


def test_grouped_pack_is_indirect_and_exact_everywhere(mesh8):
    # mesh8 (not a skipif): the sharded-deal leg MUST run on every tier-1
    # pass — the fixture fails loudly if the 8 forced host devices are gone
    """A grouped-input corpus (every encode path produces one) packs WITHOUT
    the 100M-event sort: the buffer keeps input order and lanes point at
    their segments by indirection. Every consumer of the wire — plain
    resident, streamed pieces, save/load round-trip, sharded mesh deal —
    must agree with the closed form on such a wire."""
    from surge_tpu.replay.corpus import synth_counter_corpus
    from surge_tpu.replay.engine import ResidentWire

    corpus = synth_counter_corpus(900, 40_000, seed=77)
    cfg = Config(overrides={"surge.replay.batch-size": 128,
                            "surge.replay.time-chunk": 32,
                            "surge.replay.resident-len-bucket": "exact"})
    eng = ReplayEngine(counter.make_replay_spec(), config=cfg)
    wire = eng.pack_resident(corpus.events)
    # the fast path really triggered: lanes are length-sorted but the buffer
    # is not lane-ordered
    assert wire.perm is not None
    cum = np.zeros(wire.lengths.shape[0], dtype=np.int64)
    np.cumsum(wire.lengths[:-1].astype(np.int64), out=cum[1:])
    assert not np.array_equal(wire.starts.astype(np.int64), cum)

    plain = eng.replay_resident(eng.upload_resident(wire))
    np.testing.assert_array_equal(plain.states["count"], corpus.expected_count)
    np.testing.assert_array_equal(plain.states["version"],
                                  corpus.expected_version)

    for segments in (2, 5):
        st = eng.replay_resident_streamed(wire, segments=segments)
        for name in plain.states:
            np.testing.assert_array_equal(st.states[name], plain.states[name],
                                          err_msg=f"segments={segments}")

    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        wire.save(f"{tmp}/w")
        loaded = ResidentWire.load(f"{tmp}/w")
        res = eng.replay_resident(eng.upload_resident(loaded))
        np.testing.assert_array_equal(res.states["count"],
                                      corpus.expected_count)

    # the sharded mesh deal gathers per-lane slabs straight from the indirect
    # starts (resident_mesh host-side re-pack)
    meng = ReplayEngine(counter.make_replay_spec(), config=cfg, mesh=mesh8)
    sharded = meng.prepare_resident_sharded(wire)
    sres = meng.replay_resident_sharded(sharded)
    np.testing.assert_array_equal(sres.states["count"], corpus.expected_count)
    np.testing.assert_array_equal(sres.states["version"],
                                  corpus.expected_version)


def test_streamed_indirect_wire_with_empty_aggregates():
    """Zero-length lanes occupy no buffer rows: the indirect streamed path
    must still stream (not silently fall back) and return their init state."""
    rng = np.random.default_rng(5)
    b, n = 60, 6000
    # aggregate 7, 23, 40 have NO events; others grouped ascending
    live = np.array([a for a in range(b) if a not in (7, 23, 40)])
    agg_idx = np.sort(rng.choice(live, size=n)).astype(np.int32)
    type_ids = rng.integers(0, 2, size=n).astype(np.int32)
    inc = np.where(type_ids == 0, 1, 0).astype(np.int32)
    dec = np.where(type_ids == 1, 1, 0).astype(np.int32)
    colev = ColumnarEvents(
        num_aggregates=b, agg_idx=agg_idx, type_ids=type_ids,
        cols={"increment_by": inc, "decrement_by": dec},
        derived_cols={"sequence_number": "ordinal"})
    eng = ReplayEngine(counter.make_replay_spec(), config=Config(overrides={
        "surge.replay.batch-size": 16, "surge.replay.time-chunk": 16,
        "surge.replay.resident-len-bucket": "exact"}))
    wire = eng.pack_resident(colev)
    assert int((wire.lengths == 0).sum()) == 3
    plain = eng.replay_resident(eng.upload_resident(wire))
    expected = (np.bincount(agg_idx, weights=inc, minlength=b)
                - np.bincount(agg_idx, weights=dec, minlength=b)).astype(np.int32)
    np.testing.assert_array_equal(plain.states["count"], expected)
    import unittest.mock as mock

    for segments in (2, 4):
        # count piece uploads to prove the path really streamed instead of
        # silently falling back to one plain upload
        real_upload = ReplayEngine.upload_resident
        with mock.patch.object(ReplayEngine, "upload_resident",
                               autospec=True, side_effect=real_upload) as up:
            st = eng.replay_resident_streamed(wire, segments=segments)
        assert up.call_count == segments
        np.testing.assert_array_equal(st.states["count"], expected,
                                      err_msg=f"segments={segments}")
        np.testing.assert_array_equal(st.states["version"],
                                      plain.states["version"])
