"""Sequence-parallel replay (surge_tpu.replay.seqpar): one aggregate's long
log sharded across the TIME axis of the mesh, composed with an ordered
all_gather — the framework's long-context / ring-attention analog
(SURVEY.md §5.7). Golden-checked against the scalar fold."""

import random

import jax
import numpy as np
import pytest

from surge_tpu.codec.tensor import encode_events
from surge_tpu.engine.model import fold_events
from surge_tpu.models import counter
from surge_tpu.replay.seqpar import replay_time_sharded


def _mesh():
    devs = jax.devices()
    if len(devs) < 8:
        # conftest forces 8 host devices via xla_force_host_platform_device_count;
        # a platform that cannot (real accelerator with fewer chips) lacks the
        # capability this suite shards over — skip, don't fail
        pytest.skip(f"time-sharded replay needs 8 devices, have {len(devs)}")
    return jax.sharding.Mesh(np.array(devs), ("data",))


def _long_logs(n_aggs, t_max, seed):
    rng = random.Random(seed)
    model = counter.CounterModel()
    logs = []
    for i in range(n_aggs):
        agg, state, log = f"a{i}", None, []
        for _ in range(rng.randrange(t_max // 2, t_max)):
            r = rng.random()
            if r < 0.55:
                cmd = counter.Increment(agg)
            elif r < 0.85:
                cmd = counter.Decrement(agg)
            else:
                cmd = counter.CreateNoOpEvent(agg)
            for e in model.process_command(state, cmd):
                state = model.handle_event(state, e)
                log.append(e)
        logs.append(log)
    return logs


def test_time_sharded_long_log_matches_scalar():
    mesh = _mesh()
    model = counter.CounterModel()
    spec = counter.make_replay_spec()
    # a batch whose per-lane logs are LONG relative to the batch (the regime
    # entity parallelism can't cover) and ragged; T not divisible by 8
    logs = _long_logs(5, 2003, seed=31)
    expected = [fold_events(model, None, log) for log in logs]

    enc = encode_events(spec.registry, logs)
    events = {"type_id": enc.type_ids.T.astype(np.int32)}
    for name, col in enc.cols.items():
        events[name] = col.T
    out = replay_time_sharded(counter.make_associative_fold(), spec, events,
                              mesh)
    for i, exp in enumerate(expected):
        assert int(out["count"][i]) == exp.count, i
        assert int(out["version"][i]) == exp.version, i


def test_time_sharded_resume_carry():
    mesh = _mesh()
    model = counter.CounterModel()
    spec = counter.make_replay_spec()
    logs = _long_logs(3, 600, seed=5)
    expected = [fold_events(model, None, log) for log in logs]
    cut = [len(l) // 3 for l in logs]

    def to_cols(parts):
        enc = encode_events(spec.registry, parts)
        ev = {"type_id": enc.type_ids.T.astype(np.int32)}
        for name, col in enc.cols.items():
            ev[name] = col.T
        return ev

    afold = counter.make_associative_fold()
    first = replay_time_sharded(afold, spec,
                                to_cols([l[:c] for l, c in zip(logs, cut)]),
                                mesh)
    second = replay_time_sharded(afold, spec,
                                 to_cols([l[c:] for l, c in zip(logs, cut)]),
                                 mesh, init_carry=first)
    for i, exp in enumerate(expected):
        assert int(second["count"][i]) == exp.count, i
        assert int(second["version"][i]) == exp.version, i


def test_time_sharded_shopping_cart_ragged():
    """The cart family (ragged logs, bool + multi-int state) through the
    sequence-parallel path vs the scalar fold."""
    import random as _random

    from surge_tpu.engine.model import RejectedCommand
    from surge_tpu.models import shopping_cart as sc

    mesh = _mesh()
    model = sc.CartModel()
    spec = model.replay_spec()
    rng = _random.Random(71)
    logs = []
    for i in range(6):
        st, log = None, []
        for _ in range(900 + 13 * i):
            if st is not None and st.checked_out:
                break
            try:
                r = rng.random()
                if r < 0.65:
                    cmd = sc.AddItem(str(i), rng.randrange(1, 30),
                                     rng.randrange(1, 4), rng.randrange(100, 900))
                elif r < 0.999:
                    cmd = sc.RemoveItem(str(i), rng.randrange(1, 30),
                                        rng.randrange(1, 3), rng.randrange(100, 900))
                else:
                    cmd = sc.Checkout(str(i))
                events = model.process_command(st, cmd)
            except RejectedCommand:
                continue
            for e in events:
                st = model.handle_event(st, e)
                log.append(e)
        logs.append(log)
    expected = [fold_events(model, None, log) for log in logs]

    enc = encode_events(spec.registry, logs)
    events = {"type_id": enc.type_ids.T.astype(np.int32)}
    for name, col in enc.cols.items():
        events[name] = col.T
    out = replay_time_sharded(sc.make_associative_fold(), spec, events, mesh)
    for i, exp in enumerate(expected):
        assert int(out["item_count"][i]) == exp.item_count, i
        assert int(out["total_cents"][i]) == exp.total_cents, i
        assert bool(out["checked_out"][i]) == exp.checked_out, i
        assert int(out["version"][i]) == exp.version, i


def test_time_sharded_bank_account_reset_monoid():
    """bank_account's last-writer-with-reset algebra: creates reset, updates
    gate on existence, orphan updates are no-ops — including a log whose
    create lands mid-way so the reset crosses shard boundaries."""
    import random as _random

    from surge_tpu.models import bank_account as ba

    mesh = _mesh()
    model = ba.BankAccountModel()
    spec = model.replay_spec()
    vocab = ba.Vocab()
    rng = _random.Random(83)
    logs = []
    for i in range(6):
        log = []
        # orphan updates first (no-ops), then a create deep into the log,
        # then real updates — the reset point lands in different shards
        for _ in range(100 + 37 * i):
            log.append(ba.BankAccountUpdated(str(i), 999.0))
        log.append(ba.BankAccountCreated(str(i), f"own{i}", f"sec{i}", 100.0))
        bal = 100.0
        for _ in range(700 + 11 * i):
            bal += rng.randrange(1, 30) * 0.25
            log.append(ba.BankAccountUpdated(str(i), bal))
        logs.append(log)
    expected = [fold_events(model, None, log) for log in logs]
    enc_logs = [[ba.encode_event(vocab, e) for e in log] for log in logs]

    enc = encode_events(spec.registry, enc_logs)
    events = {"type_id": enc.type_ids.T.astype(np.int32)}
    for name, col in enc.cols.items():
        events[name] = col.T
    out = replay_time_sharded(ba.make_associative_fold(), spec, events, mesh)
    for i, exp in enumerate(expected):
        got = ba.decode_state(vocab, str(i), ba.EncodedAccountState(
            created=bool(out["created"][i]),
            owner_code=int(out["owner_code"][i]),
            security_code_code=int(out["security_code_code"][i]),
            balance=float(out["balance"][i])))
        assert got is not None and got.balance == exp.balance, (i, got, exp)
        assert got.account_owner == exp.account_owner, i

    # pure-orphan log stays un-created
    orphan = [[ba.encode_event(vocab, ba.BankAccountUpdated("x", 5.0))
               for _ in range(50)]]
    enc2 = encode_events(spec.registry, orphan)
    ev2 = {"type_id": enc2.type_ids.T.astype(np.int32)}
    for name, col in enc2.cols.items():
        ev2[name] = col.T
    out2 = replay_time_sharded(ba.make_associative_fold(), spec, ev2, mesh)
    assert not bool(out2["created"][0])


def test_associativity_property():
    """combine must be associative for arbitrary summary triples (the property
    the sequence-parallel schedule relies on)."""
    import jax.numpy as jnp

    afold = counter.make_associative_fold()
    rng = np.random.default_rng(0)

    def rand_summary():
        return {"d_count": jnp.asarray(rng.integers(-5, 5, 16), jnp.int32),
                "has": jnp.asarray(rng.integers(0, 2, 16), bool),
                "last_seq": jnp.asarray(rng.integers(0, 99, 16), jnp.int32)}

    for _ in range(10):
        a, b, c = rand_summary(), rand_summary(), rand_summary()
        left = afold.combine(afold.combine(a, b), c)
        right = afold.combine(a, afold.combine(b, c))
        for k in left:
            np.testing.assert_array_equal(np.asarray(left[k]),
                                          np.asarray(right[k]))

    # bank_account's reset-aware composition must also associate, including
    # summaries where hc=True with/without trailing updates
    from surge_tpu.models import bank_account as ba

    bfold = ba.make_associative_fold()

    def rand_bank():
        return {"hc": jnp.asarray(rng.integers(0, 2, 16), bool),
                "cr_owner": jnp.asarray(rng.integers(0, 9, 16), jnp.int32),
                "cr_sec": jnp.asarray(rng.integers(0, 9, 16), jnp.int32),
                "cr_bal": jnp.asarray(rng.integers(0, 50, 16), jnp.float32),
                "upd_has": jnp.asarray(rng.integers(0, 2, 16), bool),
                "upd_bal": jnp.asarray(rng.integers(0, 50, 16), jnp.float32)}

    def norm(s):
        # fields shadowed by hc/upd_has are don't-cares; canonicalize them so
        # associativity is compared on OBSERVABLE content
        upd_bal = np.where(np.asarray(s["upd_has"]), np.asarray(s["upd_bal"]), 0)
        return {"hc": np.asarray(s["hc"]),
                "cr_owner": np.where(np.asarray(s["hc"]), np.asarray(s["cr_owner"]), 0),
                "cr_sec": np.where(np.asarray(s["hc"]), np.asarray(s["cr_sec"]), 0),
                "cr_bal": np.where(np.asarray(s["hc"]), np.asarray(s["cr_bal"]), 0),
                "upd_has": np.asarray(s["upd_has"]), "upd_bal": upd_bal}

    for _ in range(10):
        a, b, c = rand_bank(), rand_bank(), rand_bank()
        left = norm(bfold.combine(bfold.combine(a, b), c))
        right = norm(bfold.combine(a, bfold.combine(b, c)))
        for k in left:
            np.testing.assert_array_equal(left[k], right[k], err_msg=k)


# -- conformance harness + structural program cache (VERDICT r4 next #6) -------------

def test_fixture_folds_pass_conformance():
    """All three shipped decompositions satisfy the monoid laws against their
    spec's scalar step fold on randomized streams (padding included)."""
    from surge_tpu.models import bank_account as ba
    from surge_tpu.models import shopping_cart as sc
    from surge_tpu.replay.seqpar import check_associative_fold

    check_associative_fold(counter.make_associative_fold(),
                           counter.make_replay_spec(), seed=1)
    check_associative_fold(sc.make_associative_fold(), sc.make_replay_spec(),
                           seed=2)
    check_associative_fold(ba.make_associative_fold(), ba.make_replay_spec(),
                           seed=3)


def test_wrong_combine_rejected_loudly():
    """A deliberately-wrong combine (left-biased version instead of right)
    must raise from the conformance check — and replay_time_sharded runs that
    check on first use, so the bad fold can never corrupt states silently."""
    import jax.numpy as jnp
    import pytest

    from surge_tpu.replay.seqpar import (
        AssociativeFold,
        check_associative_fold,
    )

    good = counter.make_associative_fold()

    def bad_combine(a, b):
        return {
            "d_count": a["d_count"] + b["d_count"],
            "has": a["has"] | b["has"],
            # WRONG: left-biased — "first writer wins" version
            "last_seq": jnp.where(a["has"], a["last_seq"], b["last_seq"]),
        }

    bad = AssociativeFold(lift=good.lift, combine=bad_combine,
                          apply=good.apply, identity=good.identity)
    spec = counter.make_replay_spec()
    with pytest.raises(ValueError, match="violates"):
        check_associative_fold(bad, spec, seed=4)

    # the engine path runs the same check on first use of the fold
    events = {"type_id": np.zeros((16, 4), np.int32),
              "increment_by": np.ones((16, 4), np.int32),
              "decrement_by": np.zeros((16, 4), np.int32),
              "sequence_number": np.arange(1, 17, dtype=np.int32)[:, None]
              .repeat(4, axis=1)}
    with pytest.raises(ValueError, match="violates"):
        replay_time_sharded(bad, spec, events, _mesh())


def test_structurally_equal_folds_share_compiled_programs():
    """Two factory calls produce equal structural keys: the second replay hits
    the program cache instead of recompiling (r4 keyed on id(afold))."""
    from surge_tpu.replay import seqpar

    spec = counter.make_replay_spec()
    mesh = _mesh()
    logs = _long_logs(3, 200, seed=8)
    enc = encode_events(spec.registry, logs)
    events = {"type_id": enc.type_ids.T.astype(np.int32)}
    for name, col in enc.cols.items():
        events[name] = col.T

    assert (seqpar.fold_key(counter.make_associative_fold())
            == seqpar.fold_key(counter.make_associative_fold()))
    first = replay_time_sharded(counter.make_associative_fold(), spec, events,
                                mesh)
    n_programs = len(seqpar._PROGRAMS)
    second = replay_time_sharded(counter.make_associative_fold(), spec, events,
                                 mesh)
    assert len(seqpar._PROGRAMS) == n_programs  # cache hit, no recompile
    for k in first:
        np.testing.assert_array_equal(first[k], second[k])
