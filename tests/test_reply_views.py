"""Native reply/read legs (ISSUE 12): lazy record views + wire formatter.

Contract under test (log/common.py + csrc/txn.cc):

- ``surge_reply_format`` emits bytes BIT-IDENTICAL to the pure-Python twin
  ``py_reply_format`` for randomized batches, and protobuf parses them to
  exactly the messages ``record_to_msg`` builds;
- ``surge_reply_index`` + :class:`WireRecordView` observe identically to the
  LogRecords the pre-view path built (equality both directions, repr,
  tombstone None semantics, lazy headers);
- segment reads return :class:`SegmentRecordView`s equal to the Python
  decoder's LogRecords;
- the native VERBATIM replica-ingest path writes byte-identical FileLog
  artifacts to the Python path, and a follower ingesting a leader's records
  converges byte-identically with the leader's segment files (the
  replica-ingest golden compare, pinned clock).
"""

from __future__ import annotations

import os
import random

import pytest

from surge_tpu.config import default_config
from surge_tpu.log import log_service_pb2 as pb
from surge_tpu.log import native_gate as ng
from surge_tpu.log import segment as seg
from surge_tpu.log.common import (SegmentRecordView, WireRecordView,
                                  lazy_txn_reply, materialize,
                                  py_reply_format, records_from_reply)
from surge_tpu.log.file import FileLog
from surge_tpu.log.server import msg_to_record, record_to_msg
from surge_tpu.log.transport import LogRecord, TopicSpec

from tests.test_native_gate import _PinnedTime, _rand_records, needs_native


def _with_positions(records, seed: int):
    """Stamp plausible offsets/timestamps (reply records always carry
    them)."""
    rng = random.Random(seed * 31 + 7)
    out = []
    nxt = {}
    for r in records:
        k = (r.topic, r.partition)
        off = nxt.get(k, rng.randint(0, 5000))
        nxt[k] = off + 1
        out.append(LogRecord(topic=r.topic, key=r.key, value=r.value,
                             partition=r.partition, headers=dict(r.headers),
                             offset=off,
                             timestamp=rng.choice(
                                 [0.0, 1_722_000_000.25 + off / 3.0])))
    return out


@needs_native
@pytest.mark.parametrize("seed", range(25))
def test_reply_format_bit_identical_and_pb_compatible(seed):
    rng = random.Random(seed)
    records = _with_positions(_rand_records(rng), seed)
    native = ng.reply_format(records, 1)
    twin = py_reply_format(records, 1)
    assert native == twin
    parsed = pb.ReadReply.FromString(native)
    assert [msg_to_record(m) for m in parsed.records] == records
    # and protobuf's own serialization of the same messages parses back
    # equal too (field/map order differs on the wire; readers must agree)
    reserialized = pb.ReadReply(
        records=[record_to_msg(r) for r in records]).SerializeToString()
    assert [msg_to_record(m)
            for m in pb.ReadReply.FromString(reserialized).records] == records


@needs_native
@pytest.mark.parametrize("seed", range(25))
def test_wire_views_observe_identically(seed):
    rng = random.Random(seed + 1000)
    records = _with_positions(_rand_records(rng), seed)
    data = ng.reply_format(records, 1)
    views = records_from_reply(data, 1)
    assert views is not None and len(views) == len(records)
    for v, r in zip(views, records):
        assert isinstance(v, WireRecordView)
        assert v == r and r == v  # equality, both directions
        assert (v.topic, v.key, v.value, v.partition, v.offset,
                v.timestamp) == (r.topic, r.key, r.value, r.partition,
                                 r.offset, r.timestamp)
        assert dict(v.headers) == dict(r.headers)
        assert materialize(v) == r
    # a single changed record breaks equality (the comparison is real)
    if records:
        other = LogRecord(topic=records[0].topic, key="~different~",
                          value=b"x", partition=records[0].partition,
                          offset=records[0].offset,
                          timestamp=records[0].timestamp)
        assert views[0] != other


@needs_native
def test_wire_view_repr_matches_logrecord_repr():
    r = LogRecord(topic="t", key="k", value=b"v", partition=2,
                  headers={"a": "1"}, offset=9, timestamp=3.5)
    data = ng.reply_format([r], 1)
    (v,) = records_from_reply(data, 1)
    assert repr(v) == repr(r)
    assert repr(v).startswith("LogRecord(")


@needs_native
def test_lazy_txn_reply_scalars_and_records():
    recs = [LogRecord(topic="t", key="k", value=b"v", offset=4,
                      timestamp=1.5)]
    ok = pb.TxnReply(ok=True, records=[record_to_msg(r) for r in recs])
    lz = lazy_txn_reply(ok.SerializeToString())
    assert lz.ok and lz.records == recs and lz.error_kind == ""
    bad = pb.TxnReply(ok=False, error="nope", error_kind="not_leader",
                      leader_hint="h:9")
    lz2 = lazy_txn_reply(bad.SerializeToString())
    assert (lz2.ok, lz2.error, lz2.error_kind, lz2.leader_hint) == \
        (False, "nope", "not_leader", "h:9")
    assert lz2.records == []


@needs_native
@pytest.mark.parametrize("seed", range(10))
def test_segment_views_equal_python_decode(seed):
    rng = random.Random(seed + 7)
    records = [LogRecord(topic="t", key=r.key, value=r.value, partition=0,
                         headers=dict(r.headers), offset=100 + i,
                         timestamp=1.25 + i)
               for i, r in enumerate(_rand_records(rng, n_topics=1))]
    block = seg.encode_block(records, 100)
    native_recs, _ = seg.decode_block(block, 0, "t", 0, native=True)
    python_recs, _ = seg.decode_block(block, 0, "t", 0, native=False)
    assert all(isinstance(v, SegmentRecordView) for v in native_recs)
    assert all(isinstance(r, LogRecord) for r in python_recs)
    assert native_recs == python_recs == records


@needs_native
def test_verbatim_native_vs_python_artifacts_byte_identical(tmp_path):
    """append_verbatim through the native batch path writes the exact
    journal + segment bytes of the Python run-splitting path — gaps,
    interleaved partitions and multi-run batches included."""
    rng = random.Random(5)
    recs = []
    nxt = {0: 0, 1: 0}
    for i in range(40):
        p = rng.randint(0, 1)
        if rng.random() < 0.15:
            nxt[p] += rng.randint(1, 4)  # compaction-style offset hole
        recs.append(LogRecord(topic="ev", key=f"k{i}",
                              value=bytes(rng.randbytes(rng.randint(0, 60))),
                              partition=p, headers={"h": str(i % 3)},
                              offset=nxt[p], timestamp=1_722_000_100.0 + i))
        nxt[p] += 1
    roots = {}
    for native in (True, False):
        root = tmp_path / ("n" if native else "p")
        log = FileLog(str(root), config=default_config().with_overrides(
            {"surge.log.native.enabled": native}))
        log.create_topic(TopicSpec("ev", 2))
        out = log.append_verbatim(recs, allow_gaps=True)
        assert [r.offset for r in out] == [r.offset for r in recs]
        log.close()
        roots[native] = root
    for name in ("commits.log", "data/ev-0.seg", "data/ev-1.seg"):
        assert (roots[True] / name).read_bytes() == \
            (roots[False] / name).read_bytes(), name


@needs_native
def test_replica_ingest_golden_leader_follower_segments(tmp_path,
                                                        monkeypatch):
    """The replica-ingest golden compare: a leader (native assign path,
    pinned clock) commits randomized batches; followers verbatim-ingest the
    committed records — one through the native batch path, one through the
    Python path. BOTH followers' segment files must be byte-identical to
    the leader's (the convergence the compaction barrier and hwm reads rest
    on)."""
    import surge_tpu.log.file as file_mod

    monkeypatch.setattr(file_mod, "time", _PinnedTime(1_722_333_444.5))
    rng = random.Random(42)
    leader = FileLog(str(tmp_path / "leader"), config=default_config())
    leader.create_topic(TopicSpec("ev", 2))
    prod = leader.transactional_producer("p")
    shipped_batches = []  # the replication worker ships per committed txn
    for _ in range(10):
        prod.begin()
        for r in _rand_records(rng, n_topics=1):
            prod.send(LogRecord(topic="ev", key=r.key, value=r.value,
                                partition=r.partition % 2,
                                headers=dict(r.headers)))
        shipped_batches.append(list(prod.commit()))
    followers = {}
    for native in (True, False):
        root = tmp_path / ("f-native" if native else "f-python")
        f = FileLog(str(root), config=default_config().with_overrides(
            {"surge.log.native.enabled": native}))
        f.create_topic(TopicSpec("ev", 2))
        for batch in shipped_batches:
            f.append_verbatim(batch)
        f.close()
        followers[native] = root
    leader.close()
    for p in range(2):
        want = (tmp_path / "leader" / "data" / f"ev-{p}.seg").read_bytes()
        for native, root in followers.items():
            got = (root / "data" / f"ev-{p}.seg").read_bytes()
            assert got == want, f"partition {p} native={native}"


@needs_native
def test_grpc_reply_legs_end_to_end(tmp_path):
    """Over a real loopback broker: the client's Read and Transact replies
    arrive as lazy views (native deserializers registered), equal to the
    records the protobuf path would have built."""
    from surge_tpu.log.client import GrpcLogTransport
    from surge_tpu.log.server import LogServer

    log = FileLog(str(tmp_path / "log"), config=default_config())
    server = LogServer(log, port=0, config=default_config())
    port = server.start()
    client = GrpcLogTransport(f"127.0.0.1:{port}")
    try:
        client.create_topic(TopicSpec("ev", 1))
        producer = client.transactional_producer("t1")
        producer.begin()
        sent = [LogRecord(topic="ev", key=f"k{i}", value=b"v%d" % i,
                          headers={"h": str(i)}) for i in range(5)]
        for r in sent:
            producer.send(r)
        committed = producer.commit()
        assert [(r.key, r.value, r.offset) for r in committed] == \
            [(f"k{i}", b"v%d" % i, i) for i in range(5)]
        got = client.read("ev", 0)
        assert list(got) == list(committed)
        assert all(isinstance(r, WireRecordView) for r in got)
        assert dict(got[3].headers) == {"h": "3"}
        # status RPCs still answer through the lazy TxnReply wrapper
        assert server.broker_status()["native"]["enabled"] is True
        assert client.broker_status()["native"]["library"] is True
    finally:
        client.close()
        server.stop()
        log.close()


@needs_native
def test_reply_format_multibyte_topic_capacity():
    """Capacity accounting counts UTF-8 BYTES: a long CJK topic must still
    format natively (the char-count estimate under-sized the buffer and
    silently disabled the leg)."""
    topic = "订单事件流主题名称很长" * 4
    recs = [LogRecord(topic=topic, key="k", value=b"v", offset=1,
                      timestamp=1.0)]
    data = ng.reply_format(recs, 1)
    assert data is not None and data == py_reply_format(recs, 1)
    assert [msg_to_record(m)
            for m in pb.ReadReply.FromString(data).records] == recs
