"""Mesh-native resident plane (surge_tpu.replay.plane_mesh) on the forced
8-device CPU mesh — tier-1 runs these on every pass (the ``mesh8`` fixture
FAILS rather than skips when the platform lost its devices).

The load-bearing proof is golden byte-identity: the sharded slab with
device-local gather lanes, driven through incremental refresh rounds,
evict/re-admit cycles AND a partition revoke/re-grant rebalance, must serve
every aggregate byte-identical to a single-device full cold-start replay over
the same log. The Pallas tile-scan kernel under ``shard_map``
(``tile-backend = pallas``) is held to the same bar."""

import asyncio

import numpy as np
import pytest

from surge_tpu.models import counter
from surge_tpu.replay.resident_state import ResidentStatePlane

from tests.test_resident_state import (
    EVT,
    NPART,
    STATE,
    TOPIC,
    Expected,
    append_events,
    cold_restore_bytes,
    make_log,
    part_of,
    wait_caught_up,
)


def _mesh_plane(log, mesh, **kw):
    """make_plane with the mesh attached (the plane wires MeshPlane when
    surge.replay.mesh.gather=local, the legacy replicated programs else)."""
    from surge_tpu.config import default_config

    overrides = kw.pop("overrides", None) or {}
    cfg = default_config().with_overrides({
        "surge.replay.resident.capacity": kw.pop("capacity", 8),
        "surge.replay.resident.max-lag-records": kw.pop("max_lag", 4096),
        "surge.replay.resident.refresh-interval-ms": 10,
        "surge.replay.batch-size": 16,
        "surge.replay.time-chunk": 8,
        **overrides,
    })
    from surge_tpu.serialization import SerializedMessage

    return ResidentStatePlane(
        log, TOPIC, counter.make_replay_spec(), config=cfg, mesh=mesh,
        deserialize_event=lambda raw: EVT.read_event(
            SerializedMessage(key="", value=raw)),
        serialize_state=lambda a, s: STATE.write_state(s).value, **kw)


@pytest.mark.parametrize("gather", ["local", "replicated"])
def test_mesh_plane_golden_byte_identity(mesh8, gather):
    """Incremental refresh rounds across evictions, re-admissions AND a
    partition revoke/re-grant — every tracked aggregate byte-identical to the
    single-device full replay, on both mesh arms."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(30)]
        evs = []
        for i, agg in enumerate(aggs):
            evs.extend(exp.events(agg, 3 + i % 5, decrement_every=4))
        append_events(log, evs)
        plane = _mesh_plane(log, mesh8, capacity=10,
                            overrides={"surge.replay.mesh.gather": gather})
        # the operator floor rounds UP to a device multiple (8 devs: 10→16)
        assert plane.capacity == 16
        assert plane._mesh_local == (gather == "local")
        await plane.start()
        try:
            for rnd in range(3):
                evs = []
                for i, agg in enumerate(aggs):
                    if (i + rnd) % 3 == 0:
                        evs.extend(exp.events(agg, 2 + rnd,
                                              decrement_every=3))
                append_events(log, evs)
                await wait_caught_up(plane)
                if rnd == 1:
                    # indexer-style rebalance mid-tail: revoke partition 1,
                    # then re-grant — purge, re-anchor at 0, refold without
                    # double-folding (the sharded slab included)
                    plane.set_partitions([0, 2, 3])
                    assert all(part_of(a) != 1 for a in plane.resident_ids())
                    plane.set_partitions([0, 1, 2, 3])
                    await wait_caught_up(plane)
            assert plane.stats["evictions"] > 0, \
                "capacity 16 with 30 aggregates must have churned the slab"
            golden = cold_restore_bytes(log)
            for agg in aggs:
                hit, data = await plane.read_bytes(agg)
                assert hit, agg
                assert data == golden[agg], agg
            assert plane.snapshot_states() == exp.states
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_device_local_gather_correctness_across_rebalance(mesh8):
    """Batched reads resolve on the owning shard: a read_many spanning every
    shard coalesces into device-local gathers + one collective, stays correct
    across a rebalance, and the revoked partition's rows are never servable."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(32)]
        for agg in aggs:
            append_events(log, exp.events(agg, 4, decrement_every=3))
        plane = _mesh_plane(log, mesh8, capacity=32)
        await plane.start()
        try:
            assert plane._mesh_local and plane._meshp is not None
            # slots span every shard (32 slots / 8 devices = 4 rows each)
            owners = {int(plane._meshp.owners(np.asarray([s]))[0])
                      for s in plane._dir.values()}
            assert owners == set(range(8)), owners
            got = await plane.read_many(aggs)
            assert got == {a: exp.states[a] for a in aggs}
            assert plane.stats["gathers"] >= 1
            # rebalance: revoke partition 2 — its rows must MISS, the rest
            # keep serving from their shards
            plane.set_partitions([0, 1, 3])
            got = await plane.read_many(aggs)
            assert set(got) == {a for a in aggs if part_of(a) != 2}
            for a in aggs:
                hit, st = await plane.read_state(a)
                assert hit == (part_of(a) != 2)
                if hit:
                    assert st == exp.states[a]
            # re-grant: refold from 0 through fresh admissions; reads match
            plane.set_partitions([0, 1, 2, 3])
            await wait_caught_up(plane)
            got = await plane.read_many(aggs)
            assert got == {a: exp.states[a] for a in aggs}
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_mesh_narrow_overflow_refetches_wide(mesh8):
    """The u16 narrow wire under the sharded slab: the fit flags are computed
    on the psum'd TRUE values, so an overflowing row still reads exactly
    (one wide refetch, same contract as the single-device plane)."""
    async def scenario():
        log = make_log()
        plane = _mesh_plane(log, mesh8, capacity=8)
        plane._ensure_device_state()
        assert plane._gather_narrow is not None  # all-integer counter schema
        big = counter.State("agg-big", 70_000, 3)     # overflows u16
        neg = counter.State("agg-neg", -40_000, 2)    # overflows i16
        small = counter.State("agg-small", 7, 1)
        states = {"count": np.array([s.count for s in (big, neg, small)],
                                    dtype=np.int32),
                  "version": np.array([s.version for s in (big, neg, small)],
                                      dtype=np.int32)}
        plane._seed_from_host_rows(
            ["agg-big", "agg-neg", "agg-small"], states,
            np.array([3, 2, 1], dtype=np.int32),
            {"agg-big": 0, "agg-neg": 0, "agg-small": 0})
        plane._watermarks = {p: 0 for p in range(NPART)}
        plane._seeded = True
        for expect in (big, neg, small):
            hit, st = await plane.read_state(expect.aggregate_id)
            assert hit and st == expect, (st, expect)

    asyncio.run(scenario())


def test_mesh_plane_pallas_tile_backend_byte_identity(mesh8):
    """The Pallas tile-scan kernel under shard_map, end to end through the
    PLANE: mesh seed (fold_resident_sharded with tile-backend=pallas) +
    incremental rounds, byte-identical to the single-device golden replay."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(20)]
        evs = []
        for i, agg in enumerate(aggs):
            evs.extend(exp.events(agg, 2 + i % 6, decrement_every=3))
        append_events(log, evs)
        plane = _mesh_plane(log, mesh8, capacity=24, overrides={
            "surge.replay.tile-backend": "pallas",
            "surge.replay.dispatch": "select",
        })
        await plane.start()
        try:
            evs = []
            for agg in aggs[::2]:
                evs.extend(exp.events(agg, 3, decrement_every=2))
            append_events(log, evs)
            await wait_caught_up(plane)
            golden = cold_restore_bytes(log)
            for agg in aggs:
                hit, data = await plane.read_bytes(agg)
                assert hit and data == golden[agg], agg
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_refresh_round_keeps_sharded_h2d_zero_d2h(mesh8):
    """The per-shard incremental invariant: a refresh round ships each shard
    only its lanes (one sharded h2d) and pulls nothing back — the only d2h
    the plane ever does outside reads is the eviction spill."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(16)]
        for agg in aggs:
            append_events(log, exp.events(agg, 3))
        plane = _mesh_plane(log, mesh8, capacity=16)
        await plane.start()
        try:
            meshp = plane._meshp
            append_events(log, [ev for agg in aggs
                                for ev in exp.events(agg, 2)])
            await wait_caught_up(plane)
            # the deal really split the lanes: every shard owns 2 rows of
            # the 16 slots, so per-device lane buckets stay at the 8 floor
            # instead of the global 512-bucket the replicated arm dispatches
            refresh_keys = [k for k in meshp._programs if k[0] == "refresh"]
            assert refresh_keys, "refresh rounds must go through MeshPlane"
            assert all(k[2] <= 8 for k in refresh_keys), refresh_keys
            assert plane.snapshot_states() == exp.states
        finally:
            await plane.stop()

    asyncio.run(scenario())
