"""Device-resident materialized state plane (surge_tpu.replay.resident_state).

The on-chip KTable: cold-start seed that never leaves the device, the standing
incremental refresh loop, capacity-bounded admission/eviction with exact-fold-
point spill, the batched-gather read lane with its staleness fallback, and the
rebalance contract (revoke purges, re-grant refolds — never double-folds).

The load-bearing test is the golden byte-identity one: after N incremental
refresh rounds — across evictions, re-admissions and an indexer-style
partition rebalance — every tracked aggregate's serialized state must be
byte-identical to a full cold-start replay over the same log (cpu backend,
fetch-barriered pulls)."""

import asyncio
import threading

import numpy as np
import pytest

from surge_tpu.config import default_config
from surge_tpu.engine.model import fold_events
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.metrics import Metrics, engine_metrics
from surge_tpu.models import counter
from surge_tpu.replay.resident_state import ResidentStatePlane
from surge_tpu.serialization import SerializedMessage
from surge_tpu.store import InMemoryKeyValueStore, StateStoreIndexer
from surge_tpu.store.restore import restore_from_events

EVT = counter.event_formatting()
STATE = counter.state_formatting()
TOPIC = "counter-events"
NPART = 4


def part_of(agg: str) -> int:
    return int(agg.rsplit("-", 1)[1]) % NPART


def append_events(log, events):
    prod = log.transactional_producer("seed")
    prod.begin()
    for ev in events:
        msg = EVT.write_event(ev)
        prod.send(LogRecord(topic=TOPIC, partition=part_of(ev.aggregate_id),
                            key=msg.key, value=msg.value))
    prod.commit()


def make_log():
    log = InMemoryLog()
    log.create_topic(TopicSpec(TOPIC, NPART))
    return log


def make_plane(log, *, capacity=64, max_lag=4096, metrics=None, profiler=None,
               partitions=None, overrides=None, flight=None):
    cfg = default_config().with_overrides({
        "surge.replay.resident.capacity": capacity,
        "surge.replay.resident.max-lag-records": max_lag,
        "surge.replay.resident.refresh-interval-ms": 10,
        "surge.replay.batch-size": 16,
        "surge.replay.time-chunk": 8,
        **(overrides or {}),
    })
    return ResidentStatePlane(
        log, TOPIC, counter.make_replay_spec(), config=cfg,
        partitions=partitions,
        deserialize_event=lambda raw: EVT.read_event(
            SerializedMessage(key="", value=raw)),
        serialize_state=lambda a, s: STATE.write_state(s).value,
        metrics=metrics, profiler=profiler, flight=flight)


class Expected:
    """Scalar-fold oracle mirroring every event appended to the log."""

    def __init__(self):
        self.model = counter.CounterModel()
        self.states = {}
        self.seqs = {}

    def events(self, agg: str, n: int, decrement_every: int = 0):
        out = []
        for k in range(n):
            seq = self.seqs.get(agg, 0) + 1
            self.seqs[agg] = seq
            if decrement_every and k % decrement_every == decrement_every - 1:
                ev = counter.CountDecremented(agg, 1, seq)
            else:
                ev = counter.CountIncremented(agg, 1, seq)
            self.states[agg] = fold_events(self.model, self.states.get(agg), [ev])
            out.append(ev)
        return out


async def wait_caught_up(plane, timeout=20.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while plane.lag_records() > 0:
        assert asyncio.get_running_loop().time() < deadline, \
            f"refresh loop never caught up (lag {plane.lag_records()})"
        await asyncio.sleep(0.02)


def cold_restore_bytes(log):
    """Full cold-start replay over the same log (cpu backend) — the golden
    reference the resident slab must match byte for byte."""
    store = InMemoryKeyValueStore()
    restore_from_events(
        log, TOPIC, store,
        deserialize_event=lambda raw: EVT.read_event(
            SerializedMessage(key="", value=raw)),
        serialize_state=lambda a, s: STATE.write_state(s).value,
        model=counter.CounterModel(), replay_spec=counter.make_replay_spec(),
        config=default_config().with_overrides({
            "surge.replay.backend": "cpu"}))
    return dict(store.all_items())


# -- seeding ---------------------------------------------------------------------------


def test_seed_from_log_matches_scalar_fold():
    async def scenario():
        log = make_log()
        exp = Expected()
        evs = []
        for i in range(20):
            evs.extend(exp.events(f"agg-{i}", i + 1, decrement_every=3))
        append_events(log, evs)
        plane = make_plane(log)
        await plane.start()
        try:
            assert plane.occupancy() == 20
            assert plane.snapshot_states() == exp.states
            # anchored at the captured end offsets: nothing left to fold
            assert plane.lag_records() == 0
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_seed_overflow_spills_and_still_serves():
    """Aggregates past capacity are pulled once into the host spill at seed
    time and stay readable (longest logs stay resident)."""
    async def scenario():
        log = make_log()
        exp = Expected()
        evs = []
        for i in range(24):
            evs.extend(exp.events(f"agg-{i}", i + 1))
        append_events(log, evs)
        plane = make_plane(log, capacity=8)
        await plane.start()
        try:
            assert plane.occupancy() == 8
            # longest-log-first admission: the 8 longest logs are resident
            assert plane.resident_ids() == sorted(
                f"agg-{i}" for i in range(16, 24))
            assert plane.snapshot_states() == exp.states
            for agg in ("agg-2", "agg-20"):  # one spilled, one resident
                hit, st = await plane.read_state(agg)
                assert hit and st == exp.states[agg]
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- the golden acceptance test --------------------------------------------------------


def test_incremental_refresh_golden_byte_identity():
    """N incremental refresh rounds — forcing evictions, re-admissions AND a
    partition revoke/re-grant rebalance mid-tail — must leave every tracked
    aggregate byte-identical to a full cold-start replay over the same log."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(30)]
        evs = []
        for i, agg in enumerate(aggs):
            evs.extend(exp.events(agg, 3 + i % 5, decrement_every=4))
        append_events(log, evs)
        # capacity 8 << 30 aggregates: every refresh round churns the slab
        plane = make_plane(log, capacity=8)
        await plane.start()
        try:
            for rnd in range(4):
                evs = []
                # rotate the touched set so rounds admit/evict different rows
                for i, agg in enumerate(aggs):
                    if (i + rnd) % 3 == 0:
                        evs.extend(exp.events(agg, 2 + rnd, decrement_every=3))
                append_events(log, evs)
                await wait_caught_up(plane)
                if rnd == 1:
                    # indexer-style rebalance mid-tail: revoke partition 1,
                    # then re-grant it — the plane must purge, re-anchor at 0
                    # and refold WITHOUT double-folding any event
                    plane.set_partitions([0, 2, 3])
                    assert all(part_of(a) != 1 for a in plane.resident_ids())
                    plane.set_partitions([0, 1, 2, 3])
                    await wait_caught_up(plane)
            assert plane.stats["evictions"] > 0, \
                "capacity 8 with 30 aggregates must have churned the slab"
            golden = cold_restore_bytes(log)
            # the plane read path serializes through the identical chain —
            # every aggregate, resident or spilled, byte for byte
            for agg in aggs:
                hit, data = await plane.read_bytes(agg)
                assert hit, agg
                assert data == golden[agg], agg
            assert plane.snapshot_states() == exp.states
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- eviction / re-admission -----------------------------------------------------------


def test_eviction_spills_exact_fold_point_and_readmits():
    async def scenario():
        log = make_log()
        exp = Expected()
        first = [f"agg-{i}" for i in range(0, 8)]
        second = [f"agg-{i}" for i in range(8, 16)]
        evs = []
        for agg in first:
            evs.extend(exp.events(agg, 5))
        append_events(log, evs)
        from surge_tpu.observability import FlightRecorder

        flight = FlightRecorder(name="engine:t", role="engine")
        plane = make_plane(log, capacity=8,  # 8 is the plane's floor
                           flight=flight)
        await plane.start()
        try:
            assert plane.resident_ids() == sorted(first)
            # a round of brand-new aggregates evicts the old set to spill
            evs = []
            for agg in second:
                evs.extend(exp.events(agg, 5))
            append_events(log, evs)
            await wait_caught_up(plane)
            assert plane.stats["evictions"] == 8
            assert plane.resident_ids() == sorted(second)
            # the seed and the eviction are incident-timeline material
            types = [e["type"] for e in flight.events()]
            assert "resident.seed" in types and "resident.evict" in types
            evict = next(e for e in flight.events()
                         if e["type"] == "resident.evict")
            assert evict["count"] == 8 and evict["spilled"] == 8
            # evicted rows re-admit at their exact fold point on their next
            # event: 5 seeded + 2 incremental = scalar fold of all 7
            evs = []
            for agg in first:
                evs.extend(exp.events(agg, 2, decrement_every=2))
            append_events(log, evs)
            await wait_caught_up(plane)
            assert plane.resident_ids() == sorted(first)
            assert plane.snapshot_states() == exp.states
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- rebalance + prime handoff ---------------------------------------------------------


def test_rebalance_revoke_purges_regrant_refolds():
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(8)]
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 4))
        append_events(log, evs)
        from surge_tpu.observability import FlightRecorder

        flight = FlightRecorder(name="engine:t", role="engine")
        plane = make_plane(log, flight=flight)
        await plane.start()
        try:
            victim = [a for a in aggs if part_of(a) == 1]
            assert victim
            plane.set_partitions([0, 2, 3])
            reanchor = [e for e in flight.events()
                        if e["type"] == "resident.re-anchor"]
            assert reanchor and reanchor[-1]["revoked"] == [1]
            # a revoked partition's aggregates must never be servable
            for agg in victim:
                hit, _ = await plane.read_state(agg)
                assert not hit, agg
            assert plane.stats["fallbacks"] >= len(victim)
            # re-grant: anchored at 0, the refresh loop refolds the whole
            # partition — exact equality proves nothing double-folded
            plane.set_partitions([0, 1, 2, 3])
            await wait_caught_up(plane)
            assert plane.snapshot_states() == exp.states
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_regrant_racing_inflight_fold_reanchors():
    """A revoke→re-grant pair landing while a fold round is IN FLIGHT (first
    refresh windows compile for 100ms+ — slow rounds are the norm, not the
    exception) must not let that round's commit overwrite the re-grant's
    0-anchor: the round polled at the OLD watermark, so committing its
    watermark would silently skip the whole-partition refold and later
    fresh admissions would fold tail-only states (wrong count, right
    version — version rides the event's own sequence_number)."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(8)]
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 4, decrement_every=3))
        append_events(log, evs)
        plane = make_plane(log)
        plane._ensure_device_state()
        plane.seed_from_log()

        # a committed tail: the raced round has something real to fold
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 3))
        append_events(log, evs)

        loop = asyncio.get_running_loop()
        in_flight = asyncio.Event()
        rebalanced = threading.Event()
        orig = plane._encode_pack_group

        def stalled(event_logs):
            # executor side: park the round between its poll and its commit
            loop.call_soon_threadsafe(in_flight.set)
            assert rebalanced.wait(10), "test deadlock"
            return orig(event_logs)

        plane._encode_pack_group = stalled
        round_task = asyncio.ensure_future(plane._refresh_once())
        await in_flight.wait()
        plane._encode_pack_group = orig  # only the in-flight round stalls
        plane.set_partitions([0, 2, 3])      # revoke partition 1...
        plane.set_partitions([0, 1, 2, 3])   # ...and re-grant: anchor at 0
        rebalanced.set()
        assert await round_task is True

        # the raced round's commit must leave the re-grant anchor intact
        # and partition 1's aggregates rolled back, not half-committed
        assert plane._watermarks[1] == 0
        victims = [a for a in aggs if part_of(a) == 1]
        assert victims
        for agg in victims:
            assert agg not in plane._dir and agg not in plane._spill, agg

        await plane.start()  # refresh loop refolds partition 1 from 0
        try:
            await wait_caught_up(plane)
            assert plane.snapshot_states() == exp.states
            golden = cold_restore_bytes(log)
            for agg in aggs:
                hit, data = await plane.read_bytes(agg)
                assert hit, agg
                assert data == golden[agg], agg
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_prime_watermark_handoff_no_double_fold():
    """The StateStoreIndexer.prime analog: after an out-of-band seed covered
    a window, prime() must fast-forward the fold watermarks so the refresh
    loop never re-folds (and never skips) a record."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(6)]
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 4))
        append_events(log, evs)
        plane = make_plane(log)
        plane._ensure_device_state()
        plane.seed_from_log()  # anchors watermarks at the captured ends
        anchored = dict(plane._watermarks)
        # priming BACKWARD must be a no-op (max semantics) — otherwise the
        # refresh loop would double-fold the seeded window
        plane.prime({p: 0 for p in range(NPART)})
        assert plane._watermarks == anchored
        # tail past the seed, then start the loop: it folds exactly the tail
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 3, decrement_every=2))
        append_events(log, evs)
        await plane.start()
        try:
            await wait_caught_up(plane)
            assert plane.snapshot_states() == exp.states
            # forward prime skips records an out-of-band seed already covers:
            # events applied to the oracle but primed OVER never fold twice
            ghost = []
            for agg in aggs[:2]:
                ghost.extend(exp.events(agg, 1))
            before = {a: plane.snapshot_states()[a] for a in aggs[:2]}
            plane.prime({p: log.end_offset(TOPIC, p) + 1 for p in range(NPART)})
            append_events(log, ghost)
            await asyncio.sleep(0.15)
            snap = plane.snapshot_states()
            for agg in aggs[:2]:
                assert snap[agg] == before[agg], \
                    "primed-over records must not fold"
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_indexer_rebalance_mid_tail_keeps_store_consistent():
    """StateStoreIndexer.set_partitions mid-tail (the assignment the plane
    follows): a revoke keeps already-indexed keys servable, a re-grant resumes
    from the kept watermark — no record is applied twice or skipped."""
    async def scenario():
        log = InMemoryLog()
        log.create_topic(TopicSpec("state", NPART, compacted=True))
        cfg = default_config().with_overrides(
            {"surge.state-store.commit-interval-ms": 10})
        idx = StateStoreIndexer(log, "state", config=cfg)

        def put(agg, value):
            prod = log.transactional_producer("t")
            prod.begin()
            prod.send(LogRecord(topic="state", partition=part_of(agg),
                                key=agg, value=value))
            prod.commit()

        for i in range(8):
            put(f"agg-{i}", b"v1-%d" % i)
        await idx.start()
        try:
            async def settle():
                for _ in range(200):
                    if idx.total_lag() == 0:
                        return
                    await asyncio.sleep(0.01)
                raise AssertionError("indexer never caught up")

            await settle()
            wm_before = idx.indexed_watermark("state", 1)
            idx.set_partitions([0, 2, 3])
            # mid-tail: records keep landing on the revoked partition
            put("agg-1", b"v2-1")
            await asyncio.sleep(0.05)
            # revoked keys stay servable at their last-indexed value
            assert idx.get_aggregate_bytes("agg-1") == b"v1-1"
            # re-grant resumes from the kept watermark and applies the miss
            idx.set_partitions([0, 1, 2, 3])
            assert idx.indexed_watermark("state", 1) == wm_before
            await settle()
            assert idx.get_aggregate_bytes("agg-1") == b"v2-1"
        finally:
            await idx.stop()

    asyncio.run(scenario())


# -- read path -------------------------------------------------------------------------


def test_staleness_bound_and_require_current():
    async def scenario():
        log = make_log()
        exp = Expected()
        append_events(log, exp.events("agg-0", 4))
        plane = make_plane(log, max_lag=4)
        plane._ensure_device_state()
        plane.seed_from_log()  # no refresh loop: lag only grows
        hit, st = await plane.read_state("agg-0")
        assert hit and st == exp.states["agg-0"]
        # within the bound: bounded-staleness reads still hit, but the
        # entity-init contract (require_current) demands lag 0
        stale = exp.events("agg-0", 3)
        append_events(log, stale)
        hit, _ = await plane.read_state("agg-0")
        assert hit
        hit, _ = await plane.read_state("agg-0", require_current=True)
        assert not hit
        # beyond max-lag-records: even bounded-staleness reads fall back
        append_events(log, exp.events("agg-0", 3))
        hit, _ = await plane.read_state("agg-0")
        assert not hit
        assert plane.stats["fallbacks"] == 2
        # a STOPPED plane must miss outright: its freshness view is frozen
        # while the log moves on, so served hits would grow silently stale
        await plane.stop()
        hit, _ = await plane.read_state("agg-0")
        assert not hit
        assert (await plane.read_many(["agg-0"])) == {}

    asyncio.run(scenario())


def test_concurrent_reads_coalesce_into_batched_gathers():
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(32)]
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 3))
        append_events(log, evs)
        registry = Metrics()
        plane = make_plane(log, metrics=engine_metrics(registry))
        await plane.start()
        try:
            results = await asyncio.gather(
                *(plane.read_state(a) for a in aggs for _ in range(4)))
            assert all(hit for hit, _ in results)
            assert {st.aggregate_id for _, st in results} == set(aggs)
            # 128 concurrent reads ride far fewer device gathers
            assert plane.stats["gathered_rows"] == 128
            assert plane.stats["gathers"] < 128
            snap = registry.get_metrics()
            assert snap["surge.replay.resident.gather-batch-size"] > 1
            # project() batches a whole id list in one sweep
            proj = await plane.project(aggs + ["ghost-1"])
            assert proj == {a: exp.states[a] for a in aggs}
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_unschema_event_poisons_aggregate_not_the_plane():
    """An event outside the replay schema (ExceptionThrowingEvent is
    deliberately unregistered) must degrade only ITS aggregate to the host
    path; every other aggregate keeps folding on device."""
    async def scenario():
        log = make_log()
        exp = Expected()
        append_events(log, exp.events("agg-0", 3))
        append_events(log, exp.events("agg-1", 3))
        plane = make_plane(log)
        await plane.start()
        try:
            prod = log.transactional_producer("poison")
            prod.begin()
            msg = EVT.write_event(counter.ExceptionThrowingEvent("agg-0", 4, "boom"))
            prod.send(LogRecord(topic=TOPIC, partition=part_of("agg-0"),
                                key=msg.key, value=msg.value))
            prod.commit()
            append_events(log, exp.events("agg-1", 2))
            await wait_caught_up(plane)
            hit, _ = await plane.read_state("agg-0")
            assert not hit  # poisoned: host store owns it now
            hit, st = await plane.read_state("agg-1")
            assert hit and st == exp.states["agg-1"]
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_narrow_u16_overflow_triggers_wide_refetch():
    """The u16 read wire is a guess: values past the 16-bit range must flip
    the device-computed fit flag and refetch wide — correctness can never
    depend on the narrow guess."""
    async def scenario():
        log = make_log()
        plane = make_plane(log)
        plane._ensure_device_state()
        assert plane._gather_narrow is not None  # all-integer counter schema
        big = counter.State("agg-big", 70_000, 3)     # overflows u16
        neg = counter.State("agg-neg", -40_000, 2)    # overflows i16
        small = counter.State("agg-small", 7, 1)
        states = {"count": np.array([s.count for s in (big, neg, small)],
                                    dtype=np.int32),
                  "version": np.array([s.version for s in (big, neg, small)],
                                      dtype=np.int32)}
        plane._seed_from_host_rows(
            ["agg-big", "agg-neg", "agg-small"], states,
            np.array([3, 2, 1], dtype=np.int32),
            {"agg-big": 0, "agg-neg": 0, "agg-small": 0})
        plane._watermarks = {p: 0 for p in range(NPART)}
        plane._seeded = True
        for expect in (big, neg, small):
            hit, st = await plane.read_state(expect.aggregate_id)
            assert hit and st == expect, (st, expect)

    asyncio.run(scenario())

# -- failure containment ---------------------------------------------------------------


def test_partial_round_failure_reanchors_no_double_fold():
    """A refresh round that dies AFTER some fold groups committed leaves the
    slab folded past the round's (never-advanced) watermarks. The failure
    path must re-anchor every polled partition through the re-grant route
    (purge + 0-anchor), so the retry refolds from scratch instead of folding
    the committed groups' events a second time."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(24)]
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 3))
        append_events(log, evs)
        plane = make_plane(log, capacity=8)  # 24 aggregates -> 3 groups/round
        await plane.start()
        try:
            await wait_caught_up(plane)
            real = plane._fold_group
            calls = {"n": 0}

            async def dying(group, logs, parts, gens):
                calls["n"] += 1
                if calls["n"] == 2:  # the round's SECOND group: one committed
                    raise RuntimeError("injected mid-round fold failure")
                return await real(group, logs, parts, gens)

            plane._fold_group = dying
            evs = []
            for agg in aggs:
                evs.extend(exp.events(agg, 2, decrement_every=2))
            append_events(log, evs)
            deadline = asyncio.get_running_loop().time() + 10.0
            while calls["n"] < 2:
                assert asyncio.get_running_loop().time() < deadline, \
                    "injected failure never fired"
                await asyncio.sleep(0.02)
            plane._fold_group = real
            await wait_caught_up(plane)
            golden = cold_restore_bytes(log)
            for agg in aggs:
                hit, data = await plane.read_bytes(agg)
                assert hit, agg
                assert data == golden[agg], agg
            assert plane.snapshot_states() == exp.states
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_gather_error_fails_reads_over_to_host_not_hang():
    """A device/decode failure in the gather lane must resolve every queued
    future as a host-fallback miss — an entity init awaiting a stranded
    future would hang forever — and the lane must heal for later reads."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(8)]
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 3))
        append_events(log, evs)
        plane = make_plane(log)
        await plane.start()
        try:
            await wait_caught_up(plane)
            real = plane._drain_batch

            async def boom(loop, batch):
                raise RuntimeError("injected gather failure")

            plane._drain_batch = boom
            before = plane.stats["fallbacks"]
            results = await asyncio.wait_for(
                asyncio.gather(*(plane.read_state(a) for a in aggs)), 5.0)
            assert all(r == (False, None) for r in results)
            assert plane.stats["fallbacks"] >= before + len(aggs)
            # read_many rides the same lane: the whole group fails over as {}
            out = await asyncio.wait_for(plane.read_many(aggs), 5.0)
            assert out == {}
            # the lane heals: the next drain serves reads again
            plane._drain_batch = real
            hit, st = await plane.read_state(aggs[0])
            assert hit and st == exp.states[aggs[0]]
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- wide wire: device dtypes ----------------------------------------------------------


def test_decode_wide_follows_device_dtypes_and_words():
    """The wide read wire is keyed on the DEVICE dtypes: a 64-bit schema
    column canonicalized to 32-bit on device (jax_enable_x64 off, the
    default) decodes one u32 word and widens back to the schema dtype; a
    genuine device-64-bit column occupies two u32 word-rows."""
    from types import SimpleNamespace

    plane = object.__new__(ResidentStatePlane)
    plane._fields = [SimpleNamespace(name="a"), SimpleNamespace(name="b"),
                     SimpleNamespace(name="c")]
    plane._dtypes = {"a": np.dtype(np.int64), "b": np.dtype(np.int64),
                     "c": np.dtype(np.bool_)}
    plane._dev_dts = {"a": np.dtype(np.int32),  # canonicalized on device
                      "b": np.dtype(np.int64),  # genuine 64-bit (x64 on)
                      "c": np.dtype(np.bool_)}
    plane._wide_words = [max(plane._dev_dts[f.name].itemsize // 4, 1)
                         for f in plane._fields]
    assert plane._wide_words == [1, 2, 1]
    a = np.array([1, -2, 2**31 - 1], dtype=np.int32)
    b = np.array([2**40 + 7, -(2**35), 11], dtype=np.int64)
    c = np.array([True, False, True])
    bw = b.view(np.uint32).reshape(3, 2)  # little-endian u32 word pairs
    rows = [a.view(np.uint32), bw[:, 0], bw[:, 1], c.astype(np.uint32)]
    k, k_b = 3, 8
    mat = np.zeros((len(rows), k_b), dtype=np.uint32)
    for i, r in enumerate(rows):
        mat[i, :k] = r
    out = plane._decode_wide(mat, k)
    assert out["a"].dtype == np.int64 and (out["a"] == a).all()
    assert out["b"].dtype == np.int64 and (out["b"] == b).all()
    assert out["c"].dtype == np.bool_ and (out["c"] == c).all()


# -- remote log: freshness off the loop ------------------------------------------------


def test_remote_log_freshness_check_rides_executor():
    """Against a remote (broker) log every end_offset is a blocking RPC: the
    read path's freshness check must ride the executor, never the event loop
    it shares with the command path."""
    async def scenario():
        log = make_log()
        exp = Expected()
        append_events(log, exp.events("agg-0", 3))
        end_offset_threads = set()

        class RemoteFacade:
            is_remote = True  # the GrpcLogTransport marker

            def __getattr__(self, name):
                return getattr(log, name)

            def end_offset(self, topic, partition):
                end_offset_threads.add(threading.get_ident())
                return log.end_offset(topic, partition)

        plane = make_plane(RemoteFacade())
        assert plane._remote_log
        await plane.start()
        try:
            await wait_caught_up(plane)  # calls end_offset on the loop (test)
            end_offset_threads.clear()
            loop_thread = threading.get_ident()
            hit, st = await plane.read_state("agg-0")
            assert hit and st == exp.states["agg-0"]
            assert await plane.read_many(["agg-0"]) == {
                "agg-0": exp.states["agg-0"]}
            assert end_offset_threads
            assert loop_thread not in end_offset_threads
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_revoke_landing_mid_seed_is_not_resurrected():
    """The cold-start seed runs in the executor; a rebalance revoking a
    partition WHILE the seed flies must not be undone by the seed's commit —
    the post-seed reconcile purges any partition whose anchor generation
    moved, so its rows are never servable and its watermark is dropped."""
    async def scenario():
        log = make_log()
        exp = Expected()
        aggs = [f"agg-{i}" for i in range(12)]
        evs = []
        for agg in aggs:
            evs.extend(exp.events(agg, 3))
        append_events(log, evs)
        plane = make_plane(log)
        real = plane.engine.fold_resident_slab

        def folding(corpus):
            plane.set_partitions([0, 2, 3])  # the revoke lands mid-seed
            return real(corpus)

        plane.engine.fold_resident_slab = folding
        await plane.start()
        try:
            victims = [a for a in aggs if part_of(a) == 1]
            assert victims
            assert all(part_of(a) != 1 for a in plane.resident_ids())
            assert 1 not in plane._watermarks
            for a in victims:
                hit, _ = await plane.read_state(a)
                assert not hit, a
            await wait_caught_up(plane)
            for a in aggs:
                if part_of(a) != 1:
                    hit, st = await plane.read_state(a)
                    assert hit and st == exp.states[a], a
        finally:
            await plane.stop()

    asyncio.run(scenario())
