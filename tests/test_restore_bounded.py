"""Bounded-memory restore_from_events (VERDICT r4 missing #4).

The reference streams its restore in bounded batches (restore consumer
max.poll.records, common reference.conf:198-199); our equivalent must never
materialize a whole topic as per-event Python objects. Above the
``surge.replay.restore-spill-events`` threshold the tpu backend detours
through a throwaway columnar segment and the cpu backend folds in
key-hash-range passes — both byte-identical to the in-memory path.
"""

import json
import os
import subprocess
import sys

import pytest

import numpy as np

from surge_tpu.config import default_config
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.models import counter
from surge_tpu.serialization import SerializedMessage
from surge_tpu.store import InMemoryKeyValueStore
from surge_tpu.store.restore import restore_from_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _seed(log, n_agg=40, per=5):
    fmt = counter.event_formatting()
    prod = log.transactional_producer("seed")
    prod.begin()
    for i in range(n_agg):
        agg = f"agg-{i}"
        for k in range(per):
            prod.send(LogRecord(
                topic="events", key=agg,
                value=fmt.write_event(
                    counter.CountIncremented(agg, 1, k + 1)).value,
                partition=i % log.num_partitions("events")))
    prod.commit()


def _restore(log, overrides):
    fmt = counter.event_formatting()
    sfmt = counter.state_formatting()
    store = InMemoryKeyValueStore()
    res = restore_from_events(
        log, "events", store,
        deserialize_event=lambda data: fmt.read_event(
            SerializedMessage(key="", value=data)),
        serialize_state=lambda a, s: sfmt.write_state(s).value,
        model=counter.CounterModel(), replay_spec=counter.make_replay_spec(),
        config=default_config().with_overrides(
            {"surge.replay.batch-size": 16, "surge.replay.time-chunk": 8,
             **overrides}))
    return res, store


def test_bounded_paths_byte_identical_to_inmemory():
    """Forcing the spill threshold below the topic size must not change a
    single restored byte, for both backends' bounded routes."""
    log = InMemoryLog()
    log.create_topic(TopicSpec("events", 2))
    _seed(log)

    baseline, base_store = _restore(log, {"surge.replay.backend": "tpu"})
    assert baseline.num_events == 200

    for backend in ("tpu", "cpu"):
        res, store = _restore(log, {
            "surge.replay.backend": backend,
            "surge.replay.restore-spill-events": 50,  # << 200 events
            "surge.replay.restore-chunk-aggregates": 7,
        })
        assert res.backend == backend
        assert res.num_aggregates == baseline.num_aggregates == 40
        assert res.num_events == baseline.num_events
        assert res.watermarks == baseline.watermarks
        assert sorted(store.all_items()) == sorted(base_store.all_items()), backend


_CHILD = r"""
import json, resource, sys, time
sys.path.insert(0, %(repo)r)
from surge_tpu.config import default_config
from surge_tpu.log.file import FileLog
from surge_tpu.models import counter
from surge_tpu.serialization import SerializedMessage
from surge_tpu.store import InMemoryKeyValueStore
from surge_tpu.store.restore import restore_from_events

CAP_MB = %(cap_mb)d  # generous absolute backstop only — the load-bearing
# assertion is the parent's PAIRED bounded-vs-in-memory comparison
fmt = counter.event_formatting()
sfmt = counter.state_formatting()
log = FileLog(%(root)r)
store = InMemoryKeyValueStore()
res = restore_from_events(
    log, "events", store,
    deserialize_event=lambda d: fmt.read_event(SerializedMessage(key="", value=d)),
    serialize_state=lambda a, s: sfmt.write_state(s).value,
    replay_spec=counter.make_replay_spec(),
    config=default_config().with_overrides({
        "surge.replay.backend": "tpu",
        "surge.replay.restore-spill-events": %(spill_events)d,
        "surge.replay.restore-chunk-aggregates": 8192}))
peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
assert res.num_aggregates == %(n_agg)d, res
assert res.num_events == %(n_agg)d * %(per)d, res
for i in range(0, %(n_agg)d, %(n_agg)d // 100):
    st = sfmt.read_state(store.get(f"a{i}"))
    assert (st.count, st.version) == (%(per)d, %(per)d), (i, st)
assert peak_mb < CAP_MB, f"restore peaked at {peak_mb:.0f} MB (cap {CAP_MB} MB)"
print(json.dumps({"peak_rss_mb": round(peak_mb)}))
"""


def _child_jax_baseline_mb() -> float:
    """Peak RSS of a bare jax-on-cpu child on THIS container: the fixed floor
    under any restore-route measurement. Some images' jax runtime alone eats
    most of the 600 MB cap — the capability gate below skips (instead of
    failing) when the cap cannot be meaningful here."""
    probe = ("import jax, jax.numpy as jnp, resource; "
             "jnp.zeros((1,)).block_until_ready(); "
             "print(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss/1024)")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("AXON_POOL_IPS", None)
    try:
        out = subprocess.run([sys.executable, "-c", probe], env=env,
                             capture_output=True, text=True, timeout=120)
        return float(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001 — gate open: let the real test speak
        return 0.0


_JAX_BASELINE_MB = _child_jax_baseline_mb()

#: the bounded route's own working set on the calibration host was ~280 MB on
#: top of its jax runtime; a baseline above cap-280-margin leaves no headroom
_RSS_HEADROOM_GATE = _JAX_BASELINE_MB > 600 - 280 - 10


@pytest.mark.skipif(
    _RSS_HEADROOM_GATE,
    reason=f"jax runtime baseline RSS is {_JAX_BASELINE_MB:.0f} MB on this "
           "container — the jax floor alone dwarfs the bounded route's "
           "working set, so neither the backstop nor the paired separation "
           "is meaningful here")
def test_million_event_restore_under_rss_cap(tmp_path):
    """>1M-event topic restores through the bounded route in a child process
    whose peak RSS must land meaningfully BELOW the in-memory route's, paired
    under identical load (isolated calibration: bounded ~550 MB incl. jax
    runtime, in-memory ~756 MB)."""
    from surge_tpu.log.file import FileLog

    n_agg, per = 150_000, 7  # 1.05M events
    root = str(tmp_path / "log")
    log = FileLog(root, fsync="none")
    log.create_topic(TopicSpec("events", 2))
    fmt = counter.event_formatting()
    prod = log.transactional_producer("seed")
    prod.begin()
    for i in range(n_agg):
        agg = f"a{i}"
        for k in range(per):
            prod.send(LogRecord(topic="events", key=agg,
                                value=fmt.write_event(
                                    counter.CountIncremented(agg, 1, k + 1)).value,
                                partition=i % 2))
        if i % 20_000 == 19_999:
            prod.commit()
            prod.begin()
    prod.commit()
    log.close()

    # PAIRED measurement (the repo's round-6 discipline, brought to memory):
    # an absolute cap on this host is weather — the pre-PR fixed 600 MB cap
    # flaked at 621-627 in-suite vs 555-563 isolated, and a baseline-relative
    # +520/+560 budget still flaked (670 then 707 in-suite while the ISOLATED
    # bounded route measured 543-563 — the route itself never grew). So the
    # load-bearing assertion is now RELATIVE, condition-matched: the bounded
    # route's child and the in-memory route's child run back to back under
    # the same suite load, and bounded must undercut in-memory by a wide
    # margin (isolated separation is ~200 MB: ~550 vs ~756). A generous
    # absolute backstop still catches both routes ballooning together.
    backstop_mb = round(_JAX_BASELINE_MB + 700)
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "MALLOC_ARENA_MAX": "2"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("AXON_POOL_IPS", None)

    def run_child(spill_events: int, cap_mb: int) -> int:
        child = _CHILD % {"repo": REPO, "root": root, "n_agg": n_agg,
                          "per": per, "cap_mb": cap_mb,
                          "spill_events": spill_events}
        proc = subprocess.run([sys.executable, "-c", child], env=env,
                              capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])["peak_rss_mb"]

    # the backstop gates ONLY the bounded arm — the in-memory arm is
    # EXPECTED to blow past it (that excess is the point of the pairing)
    bounded = run_child(500_000, backstop_mb)  # 1.05M events >> threshold
    in_memory = run_child(-1, 1 << 20)  # negative disables spilling:
    #                                     whole-topic per-event Python
    #                                     objects, the route the bound avoids
    assert bounded < in_memory - 100, (
        f"bounded route peaked at {bounded} MB — not meaningfully below the "
        f"in-memory route's {in_memory} MB under identical load")
