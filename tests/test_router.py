"""Partitioner / assignments / tracker / router — KafkaPartitionShardRouterActorSpec
analog with probe-backed regions (SURVEY.md §4 pattern 3)."""

import asyncio

import pytest

from surge_tpu.engine.entity import Envelope
from surge_tpu.engine.partition import (
    HostPort,
    PartitionAssignments,
    PartitionTracker,
    murmur3_string_hash,
    partition_by_up_to_colon,
    partition_for_key,
)
from surge_tpu.engine.router import NoRouteError, SurgePartitionRouter

ME = HostPort("local", 1)
OTHER = HostPort("remote", 2)


# -- partitioner ------------------------------------------------------------------------


def test_murmur3_deterministic_and_signed32():
    vals = {murmur3_string_hash(s) for s in ("", "a", "ab", "agg:1", "x" * 31)}
    assert len(vals) == 5  # distinct
    for s in ("", "a", "ab", "agg:1", "x" * 31):
        h = murmur3_string_hash(s)
        assert h == murmur3_string_hash(s)
        assert -(2 ** 31) <= h < 2 ** 31


def test_partition_for_key_range_and_coverage():
    n = 8
    hits = {partition_for_key(f"agg{i}", n) for i in range(500)}
    assert hits == set(range(n))  # all partitions reachable
    for i in range(100):
        assert 0 <= partition_for_key(f"k{i}", 3) < 3
    with pytest.raises(ValueError):
        partition_for_key("x", 0)


def test_partition_by_up_to_colon():
    assert partition_by_up_to_colon("tenant:uuid-123") == "tenant"
    assert partition_by_up_to_colon("plain") == "plain"
    # co-location: same prefix -> same partition
    assert partition_for_key(partition_by_up_to_colon("t1:a"), 8) == \
        partition_for_key(partition_by_up_to_colon("t1:b"), 8)


# -- assignments + tracker --------------------------------------------------------------


def test_assignment_diff_revoked_and_added():
    pa = PartitionAssignments({ME: [0, 1, 2], OTHER: [3]})
    new, changes = pa.update({ME: [0, 2], OTHER: [3, 1]})
    assert changes.revoked[ME] == [1]
    assert changes.added[OTHER] == [1]
    assert new.partition_to_host()[1] == OTHER


def test_tracker_broadcast_and_replay_on_register():
    tracker = PartitionTracker()
    tracker.update({ME: [0]})
    seen = []
    tracker.register(lambda a, c: seen.append((dict(a.assignments), c)))
    assert seen and seen[0][0] == {ME: [0]}  # replayed current state
    tracker.update({ME: [0, 1]})
    assert seen[-1][1].added[ME] == [1]


# -- router -----------------------------------------------------------------------------


class ProbeRegion:
    """Probe-forwarding region substitute (ProbeInterceptorRegionCreator analog)."""

    def __init__(self, partition):
        self.partition = partition
        self.delivered = []
        self.stopped = False

    def deliver(self, aggregate_id, env):
        self.delivered.append((aggregate_id, env))
        if not env.reply.done():
            env.reply.set_result(f"region-{self.partition}")

    async def stop(self):
        self.stopped = True


def make_router(tracker, regions, remote=None, **kw):
    def creator(p):
        regions[p] = ProbeRegion(p)
        return regions[p]

    return SurgePartitionRouter(num_partitions=4, tracker=tracker, local_host=ME,
                                region_creator=creator, remote_deliver=remote, **kw)


def env():
    return Envelope(message="m", reply=asyncio.get_event_loop().create_future())


def test_local_delivery_routes_to_owned_partition_region():
    async def scenario():
        tracker = PartitionTracker()
        regions = {}
        router = make_router(tracker, regions)
        await router.start()
        tracker.update({ME: [0, 1, 2, 3]})
        assert router.local_partitions == [0, 1, 2, 3]

        agg = "agg42"
        e = env()
        router.deliver(agg, e)
        p = router.partition_for(agg)
        assert regions[p].delivered[0][0] == agg
        assert await e.reply == f"region-{p}"
        await router.stop()

    asyncio.run(scenario())


def test_remote_partition_forwards_through_remote_deliver():
    async def scenario():
        tracker = PartitionTracker()
        forwarded = []
        router = make_router(tracker, {}, remote=lambda hp, p, a, e: forwarded.append((hp, p, a)))
        await router.start()
        tracker.update({OTHER: [0, 1, 2, 3]})
        router.deliver("agg1", env())
        assert forwarded and forwarded[0][0] == OTHER
        assert forwarded[0][1] == router.partition_for("agg1")
        await router.stop()

    asyncio.run(scenario())


def test_no_remote_transport_fails_the_ask():
    async def scenario():
        tracker = PartitionTracker()
        router = make_router(tracker, {})
        await router.start()
        tracker.update({OTHER: [0, 1, 2, 3]})
        e = env()
        router.deliver("agg1", e)
        with pytest.raises(NoRouteError):
            await e.reply
        await router.stop()

    asyncio.run(scenario())


def test_deliveries_buffer_until_assignments_arrive():
    async def scenario():
        tracker = PartitionTracker()
        regions = {}
        router = make_router(tracker, regions)
        await router.start()
        e1, e2 = env(), env()
        router.deliver("agg1", e1)
        router.deliver("agg2", e2)
        assert not regions  # nothing known yet -> buffered
        tracker.update({ME: [0, 1, 2, 3]})
        assert await e1.reply and await e2.reply  # drained on assignment
        await router.stop()

    asyncio.run(scenario())


def test_rebalance_stops_revoked_regions():
    async def scenario():
        tracker = PartitionTracker()
        regions = {}
        router = make_router(tracker, regions)
        await router.start()
        tracker.update({ME: [0, 1, 2, 3]})
        created = dict(regions)
        tracker.update({ME: [0], OTHER: [1, 2, 3]})
        await asyncio.sleep(0)  # let the stop tasks run
        assert router.local_partitions == [0]
        assert created[1].stopped and created[2].stopped and created[3].stopped
        assert not created[0].stopped
        await router.stop()

    asyncio.run(scenario())


def test_dr_standby_defers_region_creation_until_first_message():
    async def scenario():
        tracker = PartitionTracker()
        regions = {}
        router = make_router(tracker, regions, dr_standby=True)
        await router.start()
        tracker.update({ME: [0, 1, 2, 3]})
        assert regions == {}  # standby: no eager regions
        e = env()
        router.deliver("agg1", e)
        assert len(regions) == 1  # created on first traffic
        assert await e.reply
        await router.stop()

    asyncio.run(scenario())
