"""Saga orchestration (ISSUE 19): the SagaDefinition DSL, the SagaModel
state machine, and the supervised SagaManager driving multi-aggregate
workflows exactly-once — happy path, rejection → reverse compensation,
dead-letter parking, deterministic-request-id dedup across retries, manager
restart resume, and the crash-point → supervisor-restart recovery leg."""

import asyncio
import time

import pytest

from surge_tpu import SurgeCommandBusinessLogic, create_engine
from surge_tpu.config import Config
from surge_tpu.engine.model import RejectedCommand
from surge_tpu.log import InMemoryLog
from surge_tpu.models import counter
from surge_tpu.saga import (
    COMPENSATED,
    COMPLETED,
    DEAD_LETTER,
    RUNNING,
    SagaDefinition,
    SagaManager,
    SagaStep,
    compensation_request_id,
    definition_index,
    make_saga_logic,
    step_request_id,
)
from surge_tpu.saga.model import (
    RecordStepCommitted,
    RecordStepCompensated,
    RecordStepFailed,
    SagaModel,
    StartSaga,
)
from surge_tpu.testing.faults import FaultPlane

CFG = Config(overrides={
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.saga.step-timeout-ms": 2_000,
    "surge.saga.step-backoff-ms": 20,
    "surge.saga.poll-interval-ms": 10,
})

TERMINAL_NAMES = ("completed", "compensated", "dead-letter")


def _acct_logic():
    return SurgeCommandBusinessLogic(
        aggregate_name="acct", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting())


def _transfer(poison_credit=False, poison_compensation=False):
    """Two-step transfer keyed off the saga id (``t:{src}:{dst}:{n}``)."""
    def credit_cmd(tid, s):
        if poison_credit or s.c1 >= 1.0:
            return counter.FailCommandProcessing(tid, "credit poisoned")
        return counter.Increment(tid)

    def debit_comp(tid, s):
        if poison_compensation:
            return counter.FailCommandProcessing(tid, "compensation poisoned")
        return counter.Increment(tid)

    return SagaDefinition(
        name="transfer", def_id=1,
        steps=(
            SagaStep("debit", participant="acct",
                     target=lambda sid, s: sid.split(":")[1],
                     command=lambda tid, s: counter.Decrement(tid),
                     compensation=debit_comp),
            SagaStep("credit", participant="acct",
                     target=lambda sid, s: sid.split(":")[2],
                     command=credit_cmd,
                     compensation=lambda tid, s: counter.Decrement(tid)),
        ))


async def _engines(definition, faults=None, register=True):
    log = InMemoryLog()
    acct = create_engine(_acct_logic(), log=log, config=CFG)
    saga = create_engine(make_saga_logic(), log=log, config=CFG)
    mgr = SagaManager(saga, [definition], {"acct": acct, "saga": saga},
                      config=CFG, faults=faults)
    if register:
        saga.register_saga_manager(mgr)
    await acct.start()
    await saga.start()
    return acct, saga, mgr


async def _wait_terminal(mgr, sid, timeout=20.0):
    deadline = time.monotonic() + timeout
    st = {}
    while time.monotonic() < deadline:
        st = await mgr.status(sid)
        if st["status"] in TERMINAL_NAMES:
            return st
        await asyncio.sleep(0.02)
    raise AssertionError(f"saga {sid} never reached a terminal state: {st}")


async def _count(acct, aid):
    st = await acct.aggregate_for(aid).get_state()
    return 0 if st is None else st.count


# -- the model state machine ----------------------------------------------------------


def _fold(model, state, events):
    for e in events:
        state = model.handle_event(state, e)
    return state


def test_model_happy_walk_reaches_completed():
    m = SagaModel()
    s = _fold(m, None, m.process_command(None, StartSaga("s1", 1, 2)))
    assert s.status == RUNNING and s.step == 0 and s.num_steps == 2
    s = _fold(m, s, m.process_command(s, RecordStepCommitted("s1", 0)))
    assert s.status == RUNNING and s.step == 1 and s.committed == 0b01
    s = _fold(m, s, m.process_command(s, RecordStepCommitted("s1", 1)))
    assert s.status == COMPLETED and s.committed == 0b11 and s.compensated == 0


def test_model_failure_walk_compensates_committed_bits():
    m = SagaModel()
    s = _fold(m, None, m.process_command(None, StartSaga("s2", 1, 3)))
    s = _fold(m, s, m.process_command(s, RecordStepCommitted("s2", 0)))
    s = _fold(m, s, m.process_command(s, RecordStepCommitted("s2", 1)))
    s = _fold(m, s, m.process_command(s, RecordStepFailed("s2", 2, attempts=4)))
    assert s.status != COMPLETED and s.committed == 0b011
    s = _fold(m, s, m.process_command(s, RecordStepCompensated("s2", 1)))
    assert s.status != COMPENSATED  # half-way is NOT terminal
    s = _fold(m, s, m.process_command(s, RecordStepCompensated("s2", 0)))
    assert s.status == COMPENSATED and s.compensated == s.committed


def test_model_failure_with_nothing_committed_is_immediately_compensated():
    m = SagaModel()
    s = _fold(m, None, m.process_command(None, StartSaga("s3", 1, 2)))
    s = _fold(m, s, m.process_command(s, RecordStepFailed("s3", 0, attempts=4)))
    assert s.status == COMPENSATED and s.committed == 0 and s.compensated == 0


def test_model_records_are_idempotent_by_rejection():
    m = SagaModel()
    s = _fold(m, None, m.process_command(None, StartSaga("s4", 1, 2)))
    s = _fold(m, s, m.process_command(s, RecordStepCommitted("s4", 0)))
    with pytest.raises(RejectedCommand):
        m.process_command(s, RecordStepCommitted("s4", 0))  # already folded
    with pytest.raises(RejectedCommand):
        m.process_command(s, StartSaga("s4", 1, 2))  # already started
    s = _fold(m, s, m.process_command(s, RecordStepFailed("s4", 1, attempts=2)))
    s = _fold(m, s, m.process_command(s, RecordStepCompensated("s4", 0)))
    with pytest.raises(RejectedCommand):
        m.process_command(s, RecordStepCompensated("s4", 0))
    assert s.status == COMPENSATED


def test_definition_validation_rejects_malformed_sagas():
    step = SagaStep("a", participant="p", target=lambda sid, s: sid,
                    command=lambda tid, s: None)
    with pytest.raises(ValueError):
        SagaDefinition(name="empty", def_id=1, steps=())
    with pytest.raises(ValueError):
        SagaDefinition(name="dup", def_id=1, steps=(step, step))
    with pytest.raises(ValueError):
        SagaDefinition(name="bad-id", def_id=0, steps=(step,))
    d1 = SagaDefinition(name="a", def_id=7, steps=(step,))
    d2 = SagaDefinition(name="b", def_id=7, steps=(step,))
    with pytest.raises(ValueError):
        definition_index([d1, d2])


def test_request_ids_are_deterministic_and_distinct():
    assert step_request_id("t:a:b:1", 0) == step_request_id("t:a:b:1", 0)
    assert step_request_id("t:a:b:1", 0) != step_request_id("t:a:b:1", 1)
    assert step_request_id("t:a:b:1", 0) != compensation_request_id("t:a:b:1", 0)


# -- end to end over real engines -----------------------------------------------------


def test_saga_happy_path_completes_exactly_once():
    async def run():
        acct, saga, mgr = await _engines(_transfer())
        try:
            await saga.start_saga("t:alice:bob:1", "transfer")
            st = await _wait_terminal(mgr, "t:alice:bob:1")
            assert st["status"] == "completed"
            assert st["committed"] == [0, 1] and st["compensated"] == []
            assert await _count(acct, "alice") == -1
            assert await _count(acct, "bob") == 1
            # idempotent re-start: the saga:{id}:start rid collapses the
            # double submit; nothing moves twice
            st2 = await saga.start_saga("t:alice:bob:1", "transfer")
            assert st2["status"] == "completed"
            assert await _count(acct, "bob") == 1
            verdict = mgr.reconcile()
            assert verdict["ok"] and verdict["total"] == 1
            assert verdict["counts"]["completed"] == 1
        finally:
            await saga.stop()
            await acct.stop()

    asyncio.run(run())


def test_rejected_step_compensates_in_reverse_and_nets_zero():
    async def run():
        acct, saga, mgr = await _engines(_transfer(poison_credit=True))
        try:
            await saga.start_saga("t:src:dst:9", "transfer")
            st = await _wait_terminal(mgr, "t:src:dst:9")
            assert st["status"] == "compensated"
            assert st["committed"] == [0] and st["compensated"] == [0]
            # the debit landed, then was undone; the credit never landed
            assert await _count(acct, "src") == 0
            assert await _count(acct, "dst") == 0
            assert mgr.reconcile()["ok"]
            types = [e["type"] for e in saga.flight.events()]
            assert "saga.step.reject" in types
            assert "saga.comp.commit" in types
            assert "saga.terminal" in types
        finally:
            await saga.stop()
            await acct.stop()

    asyncio.run(run())


def test_poisoned_compensation_parks_dead_letter():
    async def run():
        acct, saga, mgr = await _engines(
            _transfer(poison_credit=True, poison_compensation=True))
        try:
            await saga.start_saga("t:a:b:3", "transfer")
            st = await _wait_terminal(mgr, "t:a:b:3")
            assert st["status"] == "dead-letter"
            verdict = mgr.reconcile()
            # DEAD_LETTER is the acknowledged exception: counted, not a
            # reconciliation violation
            assert verdict["ok"] and verdict["dead_letter"] == 1
            types = [e["type"] for e in saga.flight.events()]
            assert "saga.comp.reject" in types
        finally:
            await saga.stop()
            await acct.stop()

    asyncio.run(run())


def test_entity_short_circuits_duplicate_request_ids():
    """The dedup surface under every saga retry: a re-sent request id
    answers from the publisher's completed window with the CURRENT state —
    no second fold."""
    async def run():
        acct = create_engine(_acct_logic(), log=InMemoryLog(), config=CFG)
        await acct.start()
        try:
            ref = acct.aggregate_for("k-1")
            r1 = await ref.send_command(counter.Increment("k-1"),
                                        request_id="saga:t:0:fwd")
            r2 = await ref.send_command(counter.Increment("k-1"),
                                        request_id="saga:t:0:fwd")
            assert type(r1).__name__ == "CommandSuccess"
            assert type(r2).__name__ == "CommandSuccess"
            assert r2.state.count == 1  # folded once, answered twice
            r3 = await ref.send_command(counter.Increment("k-1"),
                                        request_id="saga:t:1:fwd")
            assert r3.state.count == 2  # a fresh rid folds normally
        finally:
            await acct.stop()

    asyncio.run(run())


def test_manager_restart_resumes_in_flight_saga_exactly_once():
    """Stop the manager mid-saga, then resume with a FRESH manager instance:
    recovery is the replayed saga rows alone (no side journal), and the
    deterministic rids make the re-sent leg a dedup hit, not a double
    fold."""
    async def run():
        # a delay plane holds the first step long enough for stop() to land
        plane = FaultPlane.from_spec(
            '[{"site": "saga.step.dispatch", "action": "delay", '
            '"p": 1.0, "delay_ms": 150.0, "times": 2}]')
        log = InMemoryLog()
        acct = create_engine(_acct_logic(), log=log, config=CFG)
        saga = create_engine(make_saga_logic(), log=log, config=CFG)
        mgr1 = SagaManager(saga, [_transfer()], {"acct": acct, "saga": saga},
                           config=CFG, faults=plane)
        await acct.start()
        await saga.start()
        try:
            await mgr1.start()
            await mgr1.start_saga("t:x:y:7", "transfer")
            await mgr1.stop()  # driver dies mid-flight

            mgr2 = SagaManager(saga, [_transfer()],
                               {"acct": acct, "saga": saga}, config=CFG)
            await mgr2.start()  # resume_in_flight scans the state store
            try:
                st = await _wait_terminal(mgr2, "t:x:y:7")
                assert st["status"] == "completed"
                assert await _count(acct, "x") == -1
                assert await _count(acct, "y") == 1  # exactly once
                assert mgr2.reconcile()["ok"]
            finally:
                await mgr2.stop()
        finally:
            await saga.stop()
            await acct.stop()

    asyncio.run(run())


def test_crash_point_fires_supervisor_restart_and_stays_exactly_once():
    """The torn spot: the step command COMMITTED on the participant but the
    crash fires before RecordStepCommitted reaches the saga row. The health
    supervisor restarts the manager; the resumed driver re-sends step 0
    under the SAME rid — the participant answers from its dedup window, the
    record goes through, and the account moves exactly once."""
    async def run():
        plane = FaultPlane.from_spec(
            '[{"site": "crash.saga.record.step-committed", '
            '"action": "crash", "p": 1.0, "times": 1}]')
        log = InMemoryLog()
        acct = create_engine(_acct_logic(), log=log, config=CFG)
        saga = create_engine(make_saga_logic(), log=log, config=CFG)
        mgr = SagaManager(saga, [_transfer()], {"acct": acct, "saga": saga},
                          config=CFG, faults=plane)
        saga.register_saga_manager(mgr)  # supervised: saga-manager.*fatal
        await acct.start()
        await saga.start()
        try:
            await saga.start_saga("t:p:q:5", "transfer")
            st = await _wait_terminal(mgr, "t:p:q:5")
            assert st["status"] == "completed"
            assert await _count(acct, "p") == -1
            assert await _count(acct, "q") == 1  # no duplicated step
            types = [e["type"] for e in saga.flight.events()]
            assert "saga.manager.crash" in types  # the crash is on the ring
            assert types.count("saga.terminal") == 1
            assert mgr.reconcile()["ok"]
        finally:
            await saga.stop()
            await acct.stop()

    asyncio.run(run())
