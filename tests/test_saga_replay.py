"""Saga replay goldens: the batched tensor fold of the saga state machine
(make_replay_spec's masked-bitmask handlers) must agree with the scalar
``SagaModel.handle_event`` fold on every status transition — dense cpu,
8-device mesh-sharded resident tiles, and the device-resident plane across
evictions and re-admissions, where the incrementally-folded row must come
back byte-identical to a from-scratch replay of the same log."""

import asyncio
import random

import numpy as np

from surge_tpu.codec import encode_events
from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import fold_events
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.replay import ReplayEngine
from surge_tpu.replay.resident_state import ResidentStatePlane
from surge_tpu.saga import model as saga
from surge_tpu.serialization import SerializedMessage
from surge_tpu.store import InMemoryKeyValueStore
from surge_tpu.store.restore import restore_from_events
from surge_tpu.testing import assert_replay_matches_scalar
from surge_tpu.testing.support import random_saga_log

EVT = saga.event_formatting()
STATE = saga.state_formatting()
TOPIC = "saga-events"
NPART = 4


def random_saga_logs(n, seed=0, min_len=0):
    rng = random.Random(seed)
    logs = []
    while len(logs) < n:
        log = random_saga_log(rng, f"saga-{len(logs)}")
        if len(log) >= min_len:
            logs.append(log)
    return logs


def scalar_fold_states(logs):
    m = saga.SagaModel()
    return [fold_events(m, m.initial_state(f"saga-{i}"), log)
            for i, log in enumerate(logs)]


_FIELDS = ("def_id", "num_steps", "status", "step", "committed",
           "compensated", "version")


def assert_rows_match(res, expected):
    for i, exp in enumerate(expected):
        for f in _FIELDS:
            want = getattr(exp, f) if exp is not None else 0
            assert int(res.states[f][i]) == want, (i, f, exp)


def test_saga_dense_golden_cpu():
    logs = random_saga_logs(61, seed=3)
    expected = scalar_fold_states(logs)
    spec = saga.make_replay_spec()
    eng = ReplayEngine(spec)
    res = eng.replay_encoded(encode_events(spec.registry, logs))
    assert res.num_events == sum(len(l) for l in logs)
    assert_rows_match(res, expected)


def test_saga_replay_matches_scalar_harness():
    """The one-call testing harness over the saga family — the same check
    every model family in testing/support.py gets."""
    rng = random.Random(17)
    logs = [random_saga_log(rng, str(i)) for i in range(40)]
    assert_replay_matches_scalar(saga.SagaModel(), saga.make_replay_spec(),
                                 logs)


def test_saga_mesh_sharded_resident_golden(mesh8):
    """The resident tile loop over an 8-device mesh, including a mid-log cut
    with carried state: the saga bitmasks must survive the resume path."""
    from surge_tpu.codec.tensor import encode_events_columnar

    logs = random_saga_logs(213, seed=29)  # ragged, not device-aligned
    expected = scalar_fold_states(logs)
    spec = saga.make_replay_spec()
    cfg = Config(overrides={"surge.replay.batch-size": 64,
                            "surge.replay.time-chunk": 8})
    eng = ReplayEngine(spec, config=cfg, mesh=mesh8)
    colev = encode_events_columnar(spec.registry, logs)
    res = eng.replay_resident_sharded(eng.prepare_resident_sharded(colev))
    assert res.num_events == sum(len(l) for l in logs)
    assert_rows_match(res, expected)

    cut = [len(l) // 2 for l in logs]
    first = encode_events_columnar(spec.registry,
                                   [l[:c] for l, c in zip(logs, cut)])
    second = encode_events_columnar(spec.registry,
                                    [l[c:] for l, c in zip(logs, cut)])
    r1 = eng.replay_resident_sharded(eng.prepare_resident_sharded(first))
    r2 = eng.replay_resident_sharded(eng.prepare_resident_sharded(second),
                                     init_carry=r1.states,
                                     ordinal_base=np.asarray(cut, np.int32))
    assert_rows_match(r2, expected)


# -- the device-resident plane across evict / re-admit ---------------------------------


def part_of(agg: str) -> int:
    return int(agg.rsplit("-", 1)[1]) % NPART


def append_events(log, events):
    prod = log.transactional_producer("seed")
    prod.begin()
    for ev in events:
        msg = EVT.write_event(ev)
        prod.send(LogRecord(topic=TOPIC, partition=part_of(ev.aggregate_id),
                            key=msg.key, value=msg.value))
    prod.commit()


def make_plane(log, *, capacity):
    cfg = default_config().with_overrides({
        "surge.replay.resident.capacity": capacity,
        "surge.replay.resident.refresh-interval-ms": 10,
        "surge.replay.batch-size": 16,
        "surge.replay.time-chunk": 8,
    })
    return ResidentStatePlane(
        log, TOPIC, saga.make_replay_spec(), config=cfg,
        deserialize_event=lambda raw: EVT.read_event(
            SerializedMessage(key="", value=raw)),
        serialize_state=lambda a, s: STATE.write_state(s).value,
        metrics=None)


async def wait_caught_up(plane, timeout=20.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while plane.lag_records() > 0:
        assert asyncio.get_running_loop().time() < deadline, \
            f"refresh loop never caught up (lag {plane.lag_records()})"
        await asyncio.sleep(0.02)


def cold_restore_bytes(log):
    """From-scratch replay over the same log on the cpu backend — the
    byte-identity reference for the incrementally-folded resident rows."""
    store = InMemoryKeyValueStore()
    restore_from_events(
        log, TOPIC, store,
        deserialize_event=lambda raw: EVT.read_event(
            SerializedMessage(key="", value=raw)),
        serialize_state=lambda a, s: STATE.write_state(s).value,
        model=saga.SagaModel(), replay_spec=saga.make_replay_spec(),
        config=default_config().with_overrides({
            "surge.replay.backend": "cpu"}))
    return dict(store.all_items())


def test_saga_resident_plane_byte_identity_across_evict_readmit():
    """Three waves: seed 8 saga rows at a prefix of their logs, flood 8 new
    rows through a capacity-8 slab (evicting the first set to spill at their
    exact fold point), then land the first set's log suffixes so they
    re-admit and finish folding incrementally. Every tracked row's
    serialized state must equal the from-scratch replay byte for byte."""
    async def scenario():
        log = InMemoryLog()
        log.create_topic(TopicSpec(TOPIC, NPART))
        first_logs = random_saga_logs(8, seed=41, min_len=2)
        second_logs = [random_saga_log(random.Random(1000 + i), f"saga-{i}")
                       for i in range(8, 16)]
        # re-key the second wave onto its own ids (random_saga_logs names
        # from 0; the helper above names explicitly)
        cuts = [len(l) // 2 for l in first_logs]
        append_events(log, [e for l, c in zip(first_logs, cuts)
                            for e in l[:c]])
        plane = make_plane(log, capacity=8)
        await plane.start()
        try:
            await wait_caught_up(plane)
            assert set(plane.resident_ids()) == {f"saga-{i}"
                                                 for i in range(8)}
            append_events(log, [e for l in second_logs for e in l if l])
            await wait_caught_up(plane)
            assert plane.stats["evictions"] > 0
            # the first wave's suffixes re-admit the evicted rows at their
            # spilled fold point — no re-seed, no double fold
            append_events(log, [e for l, c in zip(first_logs, cuts)
                                for e in l[c:]])
            await wait_caught_up(plane)

            expected = cold_restore_bytes(log)
            folded = {agg: STATE.write_state(st).value
                      for agg, st in plane.snapshot_states().items()}
            assert folded == expected
        finally:
            await plane.stop()

    asyncio.run(scenario())
