"""The saga-storm chaos soak (ISSUE 19 headline proof, fast tier-1 arm).

Three seeded schedules — odd seeds kill the COORDINATOR broker mid-storm,
even seeds a partition leader; all drop/reorder link faults, restart the
SagaManager mid-flight, and drive Zipf-skewed account contention — and each
must come back **0 lost / 0 duplicated / 0 half-compensated**: every saga
terminal, every account at exactly its expected ledger value, and the
reconciliation invariant (all steps committed XOR all committed steps
compensated, dead-letter acknowledged) clean over every saga row. The full
storm rides ``SURGE_BENCH_SAGA=1`` (bench.py) and the ``@slow`` variant."""

import pytest

from surge_tpu.cluster.soak import run_saga_soak


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_saga_soak_fast_seeds(seed):
    verdict = run_saga_soak(seed, seconds=6.0, sagas=12, accounts=8)
    assert verdict["start_errors"] == [], verdict["start_errors"]
    assert verdict["started"] == 12
    assert verdict["lost"] == 0, verdict
    assert verdict["duplicated"] == 0, verdict["ledger_mismatches"]
    assert verdict["half_compensated"] == 0, verdict["reconcile"]
    assert verdict["reconcile"]["ok"], verdict["reconcile"]
    # the poison fraction guarantees both terminal families appear
    assert verdict["counts"]["completed"] > 0
    assert verdict["poisoned"] >= 1
    assert verdict["counts"]["compensated"] >= 1
    # the manager restart leg really ran, and its resume scan is on the
    # merged flight timeline (saga.manager.start resumed=N)
    assert verdict["manager_restarted"]
    assert verdict["manager_resumed"] >= 1
    # the verdict is reconstructable from the merged timeline: saga legs
    # plus the broker kill are all on the flight rings
    assert verdict["saga_events"] > 0
    assert verdict["timeline_events"] > 0
    assert verdict["victim"]
    assert verdict["victim_was_coordinator"] == bool(seed % 2)


@pytest.mark.slow
def test_saga_soak_storm_randomized():
    """The minutes-long storm: more sagas, more accounts, longer schedules —
    the same three-zeros verdict on every seed."""
    for seed in range(71, 74):
        verdict = run_saga_soak(seed, seconds=12.0, sagas=24, accounts=16,
                                partitions=6)
        assert verdict["lost"] == 0, verdict
        assert verdict["duplicated"] == 0, verdict["ledger_mismatches"]
        assert verdict["half_compensated"] == 0, verdict["reconcile"]
        assert verdict["reconcile"]["ok"], verdict["reconcile"]
        assert verdict["started"] == 24 and verdict["start_errors"] == []
