"""SLO burn-rate engine: window math per objective kind, the multiwindow
page condition, breach -> health-bus/flight/instrument side effects and
recovery, and the acceptance path — a seeded chaos kill driving a fleet-up
breach that flips the `slo` health component, lands on the merged incident
timeline, and shows in `surgetop --once --format=json`."""

import json

from conftest import free_ports
from surge_tpu.config import Config
from surge_tpu.health import HealthSignalBus
from surge_tpu.log import InMemoryLog, LogServer
from surge_tpu.metrics.exposition import Family, Sample, render_openmetrics
from surge_tpu.metrics.fleet import fleet_metrics
from surge_tpu.observability import (
    DEFAULT_SLOS,
    FederatedScraper,
    FlightRecorder,
    SLO,
    SLOEngine,
    merge_dumps,
    reconstruct_failover,
)

FAST_CFG = Config(overrides={
    "surge.slo.fast-window-ms": 10_000,
    "surge.slo.slow-window-ms": 40_000,
    "surge.slo.burn-threshold": 2.0,
})


def _gauge(name, *samples):
    fam = Family(name=name, mtype="gauge", help="")
    for labels, value in samples:
        fam.samples.append(Sample("", labels, value))
    return {name: fam}


def _counter(name, value):
    fam = Family(name=name, mtype="counter", help="")
    fam.samples.append(Sample("_total", (("instance", "i"),), value))
    return {name: fam}


# -- per-kind extraction --------------------------------------------------------------


def test_latency_kind_reads_buckets_per_instance():
    slo = SLO("lat", family="t_ms", kind="latency", objective=0.9,
              threshold=10.0)
    fam = Family(name="t_ms", mtype="histogram", help="")
    for inst, counts in (("a", (8.0, 10.0)), ("b", (1.0, 5.0))):
        labels = (("instance", inst),)
        fam.samples.append(Sample("_bucket", labels + (("le", "10"),),
                                  counts[0]))
        fam.samples.append(Sample("_bucket", labels + (("le", "+Inf"),),
                                  counts[1]))
        fam.samples.append(Sample("_count", labels, counts[1]))
    bad, total = SLOEngine._counts(slo, {"t_ms": fam})
    # a: 8/10 good -> 2 bad; b: 1/5 good -> 4 bad
    assert (bad, total) == (6.0, 15.0)


def test_availability_kind_differences_counters():
    slo = SLO("avail", family="bad", good_family="good",
              kind="availability", objective=0.99)
    fams = {**_counter("bad", 3.0), **_counter("good", 100.0)}
    # attempts = bad + good: a pure-failure window burns at full rate
    assert SLOEngine._counts(slo, fams) == (3.0, 103.0)
    # missing good counter: every attempt observed was bad
    assert SLOEngine._counts(slo, _counter("bad", 3.0)) == (3.0, 3.0)


def test_bound_kind_direction():
    gt = SLO("lag", family="g", kind="bound", objective=0.9, threshold=5.0,
             op="gt")
    lt = SLO("up", family="g", kind="bound", objective=0.9, threshold=1.0,
             op="lt")
    fams = _gauge("g", ((("instance", "a"),), 7.0), ((("instance", "b"),), 3.0))
    assert SLOEngine._counts(gt, fams) == (1.0, 2.0)  # 7 > 5 is bad
    fams = _gauge("g", ((("instance", "a"),), 0.0), ((("instance", "b"),), 1.0))
    assert SLOEngine._counts(lt, fams) == (1.0, 2.0)  # 0 < 1 is bad


# -- multiwindow condition ------------------------------------------------------------


def test_breach_requires_both_windows_and_recovers():
    """A fast-window spike alone never pages; sustained burn does; recovery
    emits the trace signal and clears the component."""
    sigs = []
    flight = FlightRecorder(role="engine")
    metrics = fleet_metrics()
    eng = SLOEngine(
        [SLO("avail", family="bad", good_family="good",
             kind="availability", objective=0.9)],
        config=FAST_CFG, metrics=metrics,
        on_signal=lambda n, l: sigs.append((n, l)), flight=flight)

    def fams(bad, good):
        return {**_counter("bad", bad), **_counter("good", good)}

    # t=0..30: clean traffic fills the slow window with good events
    for t in range(0, 31, 5):
        eng.evaluate(fams(0.0, t * 20.0), now=float(t))
    assert eng.breached() == []
    # t=35: a 100-event ALL-BAD burst — the fast window burns (100 bad of
    # its ~300-event delta = burn 3.3), the slow window is diluted by the
    # 600 good events before it (burn ~1.4): a spike alone never pages
    eng.evaluate(fams(100.0, 600.0), now=35.0)
    row = eng.status()[0]
    assert row["burn_fast"] >= 2.0 > row["burn_slow"], row
    assert not row["breached"]
    assert eng.health_component().status == "up"
    # sustained all-bad traffic (good counter frozen): the slow window
    # crosses too -> ONE breach
    for t in range(40, 75, 5):
        eng.evaluate(fams(100.0 + (t - 35) * 16, 600.0), now=float(t))
    assert eng.breached() == ["avail"]
    assert sigs.count(("slo.breach.avail", "warning")) == 1
    assert [e["type"] for e in flight.events()] == ["slo.breach"]
    assert eng.health_component().status == "degraded"
    assert metrics.registry.get_metrics()["surge.slo.breaches"] == 1.0
    # recovery: clean traffic ages the burn out of both windows
    bad = 100.0 + (70 - 35) * 16
    for t in range(75, 140, 5):
        eng.evaluate(fams(bad, 600.0 + (t - 70) * 200.0), now=float(t))
    assert eng.breached() == []
    assert ("slo.recovered.avail", "trace") in sigs
    assert [e["type"] for e in flight.events()] == ["slo.breach",
                                                    "slo.recovered"]
    assert eng.health_component().status == "up"


def test_counter_reset_clamps_instead_of_negative_burn():
    eng = SLOEngine([SLO("a", family="bad", good_family="good",
                         kind="availability", objective=0.9)],
                    config=FAST_CFG)
    eng.evaluate({**_counter("bad", 50.0), **_counter("good", 100.0)}, now=0.0)
    # the process restarted: cumulative counters went backwards
    rows = eng.evaluate({**_counter("bad", 0.0), **_counter("good", 5.0)},
                        now=5.0)
    assert rows[0]["burn_fast"] >= 0.0  # clamped, not negative/NaN


def test_missing_family_is_no_data_not_a_breach():
    eng = SLOEngine([SLO("lag", family="absent", kind="bound",
                         objective=0.9, threshold=1.0)], config=FAST_CFG)
    for t in range(0, 60, 5):
        eng.evaluate({}, now=float(t))
    assert eng.breached() == []


# -- acceptance: chaos kill -> breach -> health/timeline/surgetop ---------------------


def test_chaos_kill_drives_breach_onto_health_bus_timeline_and_surgetop():
    """The ISSUE 9 acceptance path at in-process scale: a broker dies mid
    federation, the fleet-up objective burns over threshold in both (tiny)
    windows, and the breach (a) flips the health-bus `slo` component via its
    signal, (b) lands as a flight event that merges into the incident
    timeline next to the broker's own events, (c) shows in the surgetop
    JSON snapshot."""
    import sys
    sys.path.insert(0, f"{__file__.rsplit('/tests/', 1)[0]}/tools")
    import surgetop

    import time as _time

    port, = free_ports(1)
    broker = LogServer(InMemoryLog(), port=port)
    broker.start()
    bus = HealthSignalBus()
    engine_flight = FlightRecorder(name="engine:acc", role="engine")
    now = {"t": _time.time()}
    slo = SLOEngine(
        [SLO("fleet-up", family="up", kind="bound", objective=0.9,
             threshold=1.0, op="lt")],
        config=FAST_CFG, on_signal=bus.signal_fn("slo"),
        flight=engine_flight, clock=lambda: now["t"])
    scraper = FederatedScraper([f"broker@127.0.0.1:{port}"], slo=slo,
                               clock=lambda: now["t"])
    try:
        assert scraper.scrape_once()["up"] == 1
        # seeded chaos kill: the fault plane's op=kill through the client
        from surge_tpu.log import GrpcLogTransport

        killer = GrpcLogTransport(f"127.0.0.1:{port}")
        killer.kill_broker()
        killer.close()
        for _ in range(12):
            now["t"] += 5.0  # advance both burn windows
            scraper.scrape_once()
            if slo.breached():
                break
        assert slo.breached() == ["fleet-up"]
        broker_dump = broker.flight.dump()  # in-process: survives the kill
        # (a) the health-bus slo component flipped (degraded, not down)
        assert slo.health_component().status == "degraded"
        assert any(s.name == "slo.breach.fleet-up" for s in bus.recent())
        # (b) the breach is on the merged engine+broker incident timeline
        merged = merge_dumps([broker_dump, engine_flight.dump()])
        breach = [e for e in merged if e["type"] == "slo.breach"]
        assert breach and breach[0]["lane"] == "engine"
        assert breach[0]["objective"] == "fleet-up"
        recon = reconstruct_failover(merged)  # engine-lane + broker events:
        assert recon["complete"] is False      # tolerated, not raised
        # (c) surgetop's snapshot over the same scraper shows the breach
        snap = surgetop.snapshot(scraper)
        assert snap["breached"] == ["fleet-up"]
        assert snap["instances"][0]["up"] is False
        json.dumps(snap)  # machine-readable end to end
    finally:
        scraper.stop()
        try:
            broker.stop()
        except Exception:  # noqa: BLE001 — already killed
            pass


def test_default_slos_evaluate_over_the_fleet_golden():
    """The shipped objectives run over the canned federated payload without
    error and stay quiet on its healthy numbers."""
    from tests.test_federation import golden_fleet_scrape

    scraper = golden_fleet_scrape()
    eng = SLOEngine(DEFAULT_SLOS, metrics=scraper.metrics,
                    clock=lambda: 1_700_000_000.0)
    rows = eng.evaluate(scraper.merged_families())
    assert {r["objective"] for r in rows} == {s.name for s in DEFAULT_SLOS}
    assert eng.breached() == []
    # the slo gauges joined the scraper's registry -> next render carries them
    text = render_openmetrics(scraper.metrics.registry)
    assert f"surge_slo_objectives {len(DEFAULT_SLOS)}" in text
    assert len(DEFAULT_SLOS) == 7  # + state-divergence (ISSUE 20)
