"""surgetop live console + chaos.py fleet subcommand: row extraction from
merged families, table rendering, and the tier-1 CLI smokes (`surgetop --once
--format=json` and `chaos.py fleet` against live brokers)."""

import json
import os
import sys

from conftest import free_ports
from surge_tpu.log import InMemoryLog, LogServer
from surge_tpu.metrics import engine_metrics
from surge_tpu.metrics.exposition import MetricsHTTPServer, render_openmetrics
from surge_tpu.observability import FederatedScraper, ScrapeTarget

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import chaos  # noqa: E402
import surgetop  # noqa: E402
from tests.test_exposition import (  # noqa: E402
    golden_broker_metrics,
    golden_engine_metrics,
    validate_openmetrics,
)


def _canned_scraper():
    em, bm = golden_engine_metrics(), golden_broker_metrics()
    s = FederatedScraper(
        [ScrapeTarget("b1", "broker",
                      fetch=lambda: render_openmetrics(bm.registry)),
         ScrapeTarget("e1", "engine",
                      fetch=lambda: render_openmetrics(em.registry))],
        clock=lambda: 42.0)
    s.scrape_once()
    return s


def test_fleet_rows_extract_per_instance_columns():
    rows = surgetop.fleet_rows(_canned_scraper())
    by_inst = {r["instance"]: r for r in rows}
    b1, e1 = by_inst["b1"], by_inst["e1"]
    assert b1["role"] == "broker" and b1["up"] and b1["staleness_s"] == 0.0
    assert b1["epoch"] == 2.0          # golden broker recording
    assert b1["hwm-lag"] == 0.0        # registered but never recorded
    assert e1["entities"] == 7.0       # golden engine recording
    assert e1["epoch"] is None         # engines carry no broker epoch
    assert e1["hwm-lag"] is None       # nor any hwm gauge at all


def test_render_table_handles_missing_columns_and_breaches():
    scraper = _canned_scraper()
    rows = surgetop.fleet_rows(scraper)
    slo_status = [{"objective": "fleet-up", "target": 0.99,
                   "burn_fast": 25.0, "burn_slow": 20.0, "breached": True,
                   "kind": "bound", "description": ""}]
    frame = surgetop.render_table(
        rows, slo_status, {"targets": 2, "up": 2, "errors": {}})
    assert "BREACHED: fleet-up" in frame.splitlines()[0]
    assert "max SLO burn 25.00" in frame.splitlines()[0]
    assert any("b1" in ln and "broker" in ln for ln in frame.splitlines())
    assert "-" in frame  # absent columns render as dashes, not crashes
    assert "BREACH" in frame


def test_surgetop_once_json_smoke_against_live_brokers(capsys):
    """The tier-1 CLI smoke: one JSON snapshot over real brokers."""
    ports = free_ports(2)
    brokers = []
    try:
        for port in ports:
            srv = LogServer(InMemoryLog(), port=port)
            srv.start()
            brokers.append(srv)
        em = engine_metrics()
        em.live_entities.record(9)
        http = MetricsHTTPServer(em.registry)
        http_port = http.start()
        try:
            rc = surgetop.main([
                ",".join(f"broker@127.0.0.1:{p}" for p in ports),
                f"engine@http://127.0.0.1:{http_port}/metrics",
                "--once", "--format=json"])
            assert rc == 0
            snap = json.loads(capsys.readouterr().out)
            assert snap["summary"] == {"targets": 3, "up": 3, "errors": {}}
            assert {r["role"] for r in snap["instances"]} == {"broker",
                                                              "engine"}
            engine_row = next(r for r in snap["instances"]
                              if r["role"] == "engine")
            assert engine_row["entities"] == 9.0
            # the default SLO set evaluated (quiet on a healthy fleet)
            assert {s["objective"] for s in snap["slo"]} >= {"fleet-up"}
            assert snap["breached"] == []
        finally:
            http.stop()
    finally:
        for b in brokers:
            b.stop()


def test_surgetop_table_once_smoke(capsys):
    port, = free_ports(1)
    broker = LogServer(InMemoryLog(), port=port)
    broker.start()
    try:
        rc = surgetop.main([f"broker@127.0.0.1:{port}", "--once", "--no-slo"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "surgetop — 1/1 up" in out
        assert f"127.0.0.1:{port}" in out
    finally:
        broker.stop()


def test_chaos_fleet_prints_merged_exposition(capsys):
    """chaos.py fleet: the federated payload from the CLI, grammar-valid,
    instance-labelled, with up gauges."""
    ports = free_ports(2)
    brokers = []
    try:
        for port in ports:
            srv = LogServer(InMemoryLog(), port=port)
            srv.start()
            brokers.append(srv)
        spec = ",".join(f"broker@127.0.0.1:{p}" for p in ports)
        rc = chaos.main(["fleet", spec])
        assert rc == 0
        out = capsys.readouterr().out
        validate_openmetrics(out)
        for port in ports:
            assert f'up{{instance="127.0.0.1:{port}",role="broker"}} 1' in out
        assert "surge_fleet_up_targets 2" in out
    finally:
        for b in brokers:
            b.stop()


def test_chaos_fleet_needs_specs(capsys):
    assert chaos.main(["fleet", " , "]) == 2


def test_dom_leg_column_renders_from_trace_dumps():
    """The dominant-leg column (ISSUE 14): a traced broker's DumpTraces
    feeds the per-instance `dom-leg` cell; fetch-only and untraced targets
    render "-" instead of failing the console."""
    from surge_tpu.config import Config
    from surge_tpu.tracing import Tracer

    cfg = Config(overrides={"surge.trace.tail.latency-ms": 0})
    server = LogServer(InMemoryLog(), tracer=Tracer(), config=cfg)
    port = server.start()
    try:
        from surge_tpu.log import GrpcLogTransport, LogRecord, TopicSpec

        client = GrpcLogTransport(f"127.0.0.1:{port}")
        client.create_topic(TopicSpec("t", 1))
        p = client.transactional_producer("tx")
        p.begin()
        p.send(LogRecord(topic="t", key="k", value=b"v", partition=0))
        p.commit()
        client.close()
        scraper = FederatedScraper([f"broker@127.0.0.1:{port}"])
        scraper.scrape_once()
        rows = surgetop.fleet_rows(scraper)
        assert rows[0]["dom-leg"] in (
            "journal-fsync", "reply-decode", "gate-wait", "other")
        frame = surgetop.render_table(rows, [], {"up": 1, "targets": 1,
                                                 "errors": []})
        assert "dom-leg" in frame.splitlines()[1]
        # opting out skips the DumpTraces RPCs entirely
        assert surgetop.fleet_rows(scraper,
                                   anatomy=False)[0]["dom-leg"] is None
    finally:
        server.stop()
    # canned fetch-only targets (no address): the column is "-"
    rows = surgetop.fleet_rows(_canned_scraper())
    assert all(r["dom-leg"] is None for r in rows)

def test_chaos_sagas_panel_counts_and_verdict(capsys):
    """chaos.py sagas: the operator panel off a live engine admin endpoint —
    the fleet summary with the reconciliation verdict (exit 0 when ok), one
    saga's ledger by id (exit 1 for an unknown id), and a typed error with
    exit 1 when the engine is down. The CLI runs on a worker thread (its own
    asyncio.run) against the engine loop staying live here."""
    import asyncio

    from surge_tpu import (SurgeCommandBusinessLogic, create_engine,
                           default_config)
    from surge_tpu.admin import AdminServer
    from surge_tpu.models import counter
    from surge_tpu.saga import (SagaDefinition, SagaManager, SagaStep,
                                make_saga_logic)

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.saga.poll-interval-ms": 10,
    })
    ping = SagaDefinition(
        name="ping", def_id=1,
        steps=(SagaStep("inc", participant="acct",
                        target=lambda sid, s: sid,
                        command=lambda tid, s: counter.Increment(tid),
                        compensation=lambda tid, s: counter.Decrement(tid)),))

    async def scenario():
        log = InMemoryLog()
        acct = create_engine(SurgeCommandBusinessLogic(
            aggregate_name="acct", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting()), log=log, config=cfg)
        saga = create_engine(make_saga_logic(), log=log, config=cfg)
        saga.register_saga_manager(SagaManager(
            saga, [ping], {"acct": acct, "saga": saga}, config=cfg))
        await acct.start()
        await saga.start()
        admin = AdminServer(saga)
        port = await admin.start()
        addr = f"127.0.0.1:{port}"
        try:
            st = await saga.start_saga("ping-1", "ping")
            deadline = asyncio.get_running_loop().time() + 20
            while st["status"] not in ("completed", "compensated",
                                       "dead-letter"):
                assert asyncio.get_running_loop().time() < deadline, st
                await asyncio.sleep(0.02)
                st = await saga.saga_status("ping-1")
            assert st["status"] == "completed"

            # fleet summary: verdict ok → exit 0
            assert await asyncio.to_thread(chaos.main, ["sagas", addr]) == 0
            payload = json.loads(capsys.readouterr().out)
            assert payload["ok"] and payload["counts"]["completed"] == 1
            assert payload["violations"] == []
            # one saga's ledger by id
            assert await asyncio.to_thread(
                chaos.main, ["sagas", addr, "ping-1"]) == 0
            ledger = json.loads(capsys.readouterr().out)
            assert ledger["status"] == "completed"
            assert ledger["committed"] == [0]
            # an unknown id exits 1
            assert await asyncio.to_thread(
                chaos.main, ["sagas", addr, "nope"]) == 1
            capsys.readouterr()
        finally:
            await admin.stop()
            await saga.stop()
            await acct.stop()
        return addr

    addr = asyncio.run(scenario())
    # a down engine: typed error, exit 1
    assert chaos.main(["sagas", addr]) == 1
    err = json.loads(capsys.readouterr().out)
    assert "error" in err
