"""surge_tpu.testing — the user-facing engine doubles (SURVEY §4 item 8: the
reference's documented mockable-engine pattern)."""

import asyncio

import pytest

from surge_tpu.engine.entity import (
    CommandFailure,
    CommandRejected,
    CommandSuccess,
)
from surge_tpu.models import counter
from surge_tpu.testing import StubAggregateRef, StubEngine


def run(coro):
    return asyncio.run(coro)


def test_stub_ref_runs_real_model_logic():
    ref = StubAggregateRef("a-1", model=counter.CounterModel())

    async def scenario():
        r1 = await ref.send_command(counter.Increment("a-1"))
        r2 = await ref.send_command(counter.Decrement("a-1"))
        return r1, r2

    r1, r2 = run(scenario())
    assert isinstance(r1, CommandSuccess) and r1.state.count == 1
    assert isinstance(r2, CommandSuccess) and r2.state.count == 0
    assert r2.state.version == 2
    assert [type(c).__name__ for c in ref.commands] == ["Increment", "Decrement"]


def test_stub_ref_rejection_surfaces_like_real_engine():
    ref = StubAggregateRef("a-1", model=counter.CounterModel())
    r = run(ref.send_command(counter.FailCommandProcessing(
        "a-1", RuntimeError("nope"))))
    assert isinstance(r, CommandRejected)


def test_canned_replies_and_fail_with():
    ref = StubAggregateRef("a-1", model=counter.CounterModel())
    ref.fail_with(TimeoutError("publish timeout"))

    async def scenario():
        first = await ref.send_command(counter.Increment("a-1"))
        second = await ref.send_command(counter.Increment("a-1"))
        return first, second

    first, second = run(scenario())
    assert isinstance(first, CommandFailure)
    assert isinstance(first.error, TimeoutError)
    assert isinstance(second, CommandSuccess)  # canned reply consumed
    assert second.state.count == 1  # the failed call did not mutate state


def test_stub_ref_without_model_demands_canned_reply():
    ref = StubAggregateRef("a-1")
    r = run(ref.send_command(counter.Increment("a-1")))
    assert isinstance(r, CommandFailure)
    assert "no model" in str(r.error)


def test_apply_events_and_get_state():
    ref = StubAggregateRef("a-1", model=counter.CounterModel())

    async def scenario():
        r = await ref.apply_events(
            [counter.CountIncremented("a-1", 3, 1)])
        st = await ref.get_state()
        return r, st

    r, st = run(scenario())
    assert isinstance(r, CommandSuccess) and st.count == 3
    assert ref.applied and len(ref.applied[0]) == 1

    # canned get_state failure raises, like the real ref
    ref.reply_with(CommandFailure(ConnectionError("down")))
    with pytest.raises(ConnectionError):
        run(ref.get_state())


def test_stub_engine_shares_state_and_journals_commands():
    engine = StubEngine(model=counter.CounterModel())
    engine.seed_state({"warm": counter.State("warm", count=7, version=3)})

    async def scenario():
        assert (await engine.aggregate_for("warm").get_state()).count == 7
        await engine.aggregate_for("a").send_command(counter.Increment("a"))
        await engine.aggregate_for("b").send_command(counter.Increment("b"))
        await engine.aggregate_for("a").send_command(counter.Increment("a"))
        await engine.start()  # lifecycle no-ops exist for service code
        await engine.stop()

    run(scenario())
    # the same ref instance is returned per id, state survives across calls
    assert engine.aggregate_for("a").state.count == 2
    assert engine.states["b"].count == 1
    # cross-aggregate journal preserves SEND order
    assert [(type(c).__name__, c.aggregate_id) for c in engine.commands] == [
        ("Increment", "a"), ("Increment", "b"), ("Increment", "a")]


def test_stub_matches_real_entity_error_semantics():
    """Parity with engine/entity.py: RejectedCommand -> CommandRejected; any
    OTHER process_command exception -> CommandFailure (a stub that mapped all
    exceptions to rejection would green-light the wrong service branch)."""

    class BuggyModel:
        def initial_state(self, agg_id):
            return None

        def process_command(self, state, command):
            raise RuntimeError("infra bug, not a domain rejection")

        def handle_event(self, state, event):
            return state

    r = run(StubAggregateRef("a", model=BuggyModel()).send_command("cmd"))
    assert isinstance(r, CommandFailure) and not isinstance(r, CommandRejected)


def test_stub_supports_async_models():
    """Async process_command (the multilanguage-bridge model shape) is awaited
    inline, like the real single-writer entity."""

    class AsyncCounter:
        def initial_state(self, agg_id):
            return 0

        async def process_command(self, state, command):
            return [command]

        def handle_event(self, state, event):
            return state + event

    ref = StubAggregateRef("a", model=AsyncCounter())

    async def scenario():
        await ref.send_command(5)
        return await ref.send_command(2)

    r = run(scenario())
    assert isinstance(r, CommandSuccess) and r.state == 7


def test_assert_replay_matches_scalar_passes_and_catches_divergence():
    from surge_tpu.testing import assert_replay_matches_scalar

    model = counter.CounterModel()
    logs = [[counter.CountIncremented(f"g-{i}", 1, k + 1) for k in range(i + 1)]
            for i in range(6)]
    assert_replay_matches_scalar(model, counter.make_replay_spec(), logs)

    # a model whose scalar fold disagrees with the replay spec must be caught
    class SkewedModel(counter.CounterModel):
        def handle_event(self, state, event):
            st = super().handle_event(state, event)
            if st is not None and st.count >= 3:
                return type(st)(st.aggregate_id, st.count + 1, st.version)
            return st

    import pytest

    with pytest.raises(AssertionError, match="diverges"):
        assert_replay_matches_scalar(SkewedModel(),
                                     counter.make_replay_spec(), logs)


def test_assert_replay_matches_scalar_vocab_models_and_empty_logs():
    """The packaged golden check covers encode-hook models (bank_account's
    Vocab) and empty logs (baseline = the spec's initial record, never a
    vacuous pass)."""
    from surge_tpu.models import bank_account as ba
    from surge_tpu.testing import assert_replay_matches_scalar

    vocab = ba.Vocab()
    model = ba.BankAccountModel()
    logs = []
    for i in range(3):
        st, log = None, []
        cmds = [ba.CreateAccount(f"b-{i}", f"own-{i}", f"sec-{i}", 100.25),
                ba.CreditAccount(f"b-{i}", 10.50),
                ba.DebitAccount(f"b-{i}", 0.25)]
        for cmd in cmds:
            for e in model.process_command(st, cmd):
                st = model.handle_event(st, e)
                log.append(e)
        logs.append(log)
    logs.append([])  # empty log: compared against the initial record
    assert_replay_matches_scalar(
        model, ba.make_replay_spec(), logs,
        fields=["balance"],
        encode=lambda e: ba.encode_event(vocab, e))


def test_zipf_keys_distribution_matches_pmf_and_is_seed_stable():
    """The seedable Zipf sampler (ROADMAP 5(a)): empirical frequencies track
    the exact pmf, rank 0 dominates, the tail is long, and the same seed
    replays the same draw sequence (the soak's schedule determinism rests
    on this)."""
    import random

    from surge_tpu.testing.support import ZipfKeys

    keys = ZipfKeys(random.Random(7), n=100, s=1.1, prefix="acct-")
    draws = [keys.rank() for _ in range(20_000)]
    freq = [draws.count(r) / len(draws) for r in range(100)]
    # the head matches its exact probability within sampling noise
    for r in (0, 1, 2, 5):
        assert abs(freq[r] - keys.pmf(r)) < 0.01, (r, freq[r], keys.pmf(r))
    # skew: the hottest key beats every mid-tail key, the tail is touched
    assert freq[0] > 4 * freq[20]
    assert sum(1 for r in range(50, 100) if freq[r] > 0) > 25
    assert abs(sum(keys.pmf(r) for r in range(100)) - 1.0) < 1e-9
    # seed stability + prefix surface
    again = ZipfKeys(random.Random(7), n=100, s=1.1, prefix="acct-")
    assert [again.rank() for _ in range(200)] == draws[:200]
    assert again.draw().startswith("acct-")
    with pytest.raises(ValueError):
        ZipfKeys(random.Random(1), n=0)


def test_random_saga_log_rides_the_real_command_path():
    """The saga log generator only ever emits folds the REAL SagaModel
    accepts — every log replays cleanly through the scalar fold and covers
    the status space (running / completed / compensated / dead-letter)
    across seeds."""
    import random

    from surge_tpu.engine.model import fold_events
    from surge_tpu.saga import model as saga
    from surge_tpu.testing.support import random_saga_log

    rng = random.Random(23)
    statuses = set()
    m = saga.SagaModel()
    for i in range(200):
        log = random_saga_log(rng, f"s-{i}")
        st = fold_events(m, None, log)  # raises on an illegal fold
        if st is None:
            continue
        statuses.add(st.status)
        # sequence numbers are the aggregate's contiguous journal
        assert [e.sequence_number for e in log] == list(range(1, len(log) + 1))
    assert {saga.RUNNING, saga.COMPLETED, saga.COMPENSATED,
            saga.DEAD_LETTER} <= statuses
