"""gRPC transport security (the KafkaSecurityConfiguration analog): node transport
over real TLS with a self-signed CA, plus plaintext fallback when disabled."""

import asyncio
import subprocess

import pytest

from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
from surge_tpu.engine.entity import CommandSuccess
from surge_tpu.engine.partition import HostPort, PartitionTracker
from surge_tpu.log import InMemoryLog
from surge_tpu.models import counter
from surge_tpu.remote import GrpcRemoteDeliver, NodeTransportServer

A = HostPort("node-a", 1)
B = HostPort("node-b", 2)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """Self-signed CA + a localhost server certificate."""
    d = tmp_path_factory.mktemp("certs")
    ca_key, ca_crt = str(d / "ca.key"), str(d / "ca.crt")
    srv_key, srv_csr, srv_crt = str(d / "s.key"), str(d / "s.csr"), str(d / "s.crt")
    ext = str(d / "ext.cnf")
    run = lambda *args: subprocess.run(args, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes", "-keyout", ca_key,
        "-out", ca_crt, "-days", "1", "-subj", "/CN=surge-test-ca")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes", "-keyout", srv_key,
        "-out", srv_csr, "-subj", "/CN=localhost")
    with open(ext, "w") as f:
        f.write("subjectAltName=DNS:localhost,IP:127.0.0.1\n")
    run("openssl", "x509", "-req", "-in", srv_csr, "-CA", ca_crt, "-CAkey", ca_key,
        "-CAcreateserial", "-out", srv_crt, "-days", "1", "-extfile", ext)
    return {"ca": ca_crt, "cert": srv_crt, "key": srv_key}


def make_logic():
    return SurgeCommandBusinessLogic(
        aggregate_name="counter", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting(),
        command_format=counter.command_formatting())


def test_node_transport_over_tls(certs):
    async def scenario():
        tls_cfg = default_config().with_overrides({
            "surge.producer.flush-interval-ms": 5,
            "surge.producer.ktable-check-interval-ms": 5,
            "surge.state-store.commit-interval-ms": 20,
            "surge.aggregate.init-retry-interval-ms": 5,
            "surge.engine.num-partitions": 4,
            "surge.grpc.tls.enabled": True,
            "surge.grpc.tls.cert-file": certs["cert"],
            "surge.grpc.tls.key-file": certs["key"],
            "surge.grpc.tls.root-ca-file": certs["ca"],
        })
        log, tracker = InMemoryLog(), PartitionTracker()
        engines, servers, delivers = {}, {}, {}
        for host in (A, B):
            deliver = GrpcRemoteDeliver(make_logic(), config=tls_cfg)
            delivers[host] = deliver
            engines[host] = create_engine(make_logic(), log=log, config=tls_cfg,
                                          local_host=host, tracker=tracker,
                                          remote_deliver=deliver)
        for host in (A, B):
            await engines[host].start()
            servers[host] = NodeTransportServer(engines[host], host="localhost")
            port = await servers[host].start()
            for d in delivers.values():
                d.set_address(host, f"localhost:{port}")
        tracker.update({A: [0, 1], B: [2, 3]})

        crossed = 0
        for i in range(20):
            agg = f"agg-{i}"
            r = await engines[A].aggregate_for(agg).send_command(
                counter.Increment(agg))
            assert isinstance(r, CommandSuccess) and r.state.count == 1, (i, r)
            if engines[A].router.partition_for(agg) in (2, 3):
                crossed += 1
        assert crossed > 0  # commands really crossed the encrypted link

        for host in (A, B):
            await servers[host].stop()
            await engines[host].stop()
            await delivers[host].close()

    asyncio.run(scenario())


def test_tls_requires_cert_and_key():
    from surge_tpu.remote.security import server_credentials

    cfg = default_config().with_overrides({"surge.grpc.tls.enabled": True})
    with pytest.raises(ValueError, match="cert-file"):
        server_credentials(cfg)


def test_plaintext_default_unchanged():
    from surge_tpu.remote.security import tls_enabled

    assert not tls_enabled(default_config())
    assert not tls_enabled(None)
