"""Tier-1 CLI smokes for the repo tooling satellites (ISSUE 20):
`tools/bench_trend.py` (BENCH_*.json trajectory merge, machine-readable
last line), `tools/regen_golden_metrics.py --check` (verify-without-writing
drift gate over all three goldens), and the `chaos.py audit` down-engine
verdict."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench_trend  # noqa: E402
import chaos  # noqa: E402
import regen_golden_metrics  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- bench_trend ----------------------------------------------------------------------


def _write(path, payload):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)


def test_bench_trend_merges_fabricated_series(tmp_path, capsys):
    """Every envelope shape the repo's BENCH files use lands as one
    trajectory row; rounds order the series; the json format's LAST stdout
    line is the machine-readable summary with per-metric first/last/delta."""
    _write(tmp_path / "BENCH_FOLD_r01.json",
           {"metric": "fold_events_per_sec", "value": 100.0,
            "unit": "events/s"})
    _write(tmp_path / "BENCH_FOLD_r02.json",
           {"metric": "fold_events_per_sec", "value": 150.0,
            "unit": "events/s"})
    _write(tmp_path / "BENCH_RUN_r03.json", {"rc": 0})  # runner envelope
    _write(tmp_path / "BENCH_LADDER_r04.json",  # nested paired-ladder notes
           {"arms": [{"baseline": {"commands_per_sec_median": 900.0}},
                     {"candidate": {"commands_per_sec_median": 1000.0}}]})
    _write(tmp_path / "BENCH_SMOKE_r05.json",  # device smoke sweep
           {"smoke": {"configs": [{"events_per_sec": 5.0},
                                  {"events_per_sec": 9.0}]}})

    rc = bench_trend.main(["--dir", str(tmp_path), "--format", "json"])
    out = capsys.readouterr().out
    assert rc == 0
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["files"] == 5
    fold = tail["series"]["fold_events_per_sec"]
    # two explicit-metric rows plus the smoke sweep's best rate
    assert fold["points"] == 3
    assert fold["first"] == 100.0
    assert fold["delta_pct"] is not None
    assert tail["series"]["bench_exit_code"]["last"] == 0
    assert tail["series"]["commands_per_sec_median"]["last"] == 1000.0
    # the human table rode stdout before the machine line
    assert "fold_events_per_sec" in out.splitlines()[0] or \
        any("fold_events_per_sec" in line for line in out.splitlines()[:-1])


def test_bench_trend_on_real_repo_series(capsys):
    """The checked-in BENCH_*.json series parses end to end: every file
    yields a row and at least the ladder medians form a series."""
    rc = bench_trend.main(["--dir", REPO, "--format", "json"])
    out = capsys.readouterr().out
    assert rc == 0
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["files"] >= 10
    assert "commands_per_sec_median" in tail["series"]


def test_bench_trend_rejects_missing_dir(tmp_path, capsys):
    assert bench_trend.main(["--dir", str(tmp_path / "nope")]) == 2


def test_bench_trend_survives_unreadable_json(tmp_path, capsys):
    (tmp_path / "BENCH_BAD_r01.json").write_text("{not json", "utf-8")
    rc = bench_trend.main(["--dir", str(tmp_path), "--format", "json"])
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and tail["files"] == 1 and tail["series"] == {}


# -- regen_golden_metrics --check -----------------------------------------------------


def test_regen_check_passes_on_checked_in_goldens(capsys):
    """The CI gate: the three checked-in goldens match the canonical
    renders right now (this test IS the drift alarm for this repo)."""
    assert regen_golden_metrics.main(["--check"]) == 0
    out = capsys.readouterr().out
    assert out.count("ok ") == 3


def test_regen_check_detects_drift_without_writing(tmp_path, capsys,
                                                   monkeypatch):
    """A stale golden exits 1 naming the file and the first differing line,
    and the file on disk is NOT rewritten (verify-only); restoring the
    rendered text flips it back to 0."""
    import test_exposition

    stale = tmp_path / "metrics.om"
    stale.write_text("# stale golden\n", "utf-8")
    monkeypatch.setattr(test_exposition, "GOLDEN_PATH", str(stale))
    assert regen_golden_metrics.main(["--check"]) == 1
    out = capsys.readouterr().out
    assert f"DRIFT {stale}" in out
    assert stale.read_text("utf-8") == "# stale golden\n"  # untouched

    # a missing golden is drift too, not a crash
    monkeypatch.setattr(test_exposition, "GOLDEN_PATH",
                        str(tmp_path / "missing.om"))
    assert regen_golden_metrics.main(["--check"]) == 1
    assert "golden missing" in capsys.readouterr().out

    # write the canonical render: check goes green
    for path, text in regen_golden_metrics._renders():
        if path == str(tmp_path / "missing.om"):
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
    assert regen_golden_metrics.main(["--check"]) == 0


# -- chaos audit: down engine ---------------------------------------------------------


def test_chaos_audit_down_engine_exits_one(capsys):
    """An unreachable engine is itself the finding: exit 1 with a
    machine-readable {"ok": false, "error": ...} line."""
    rc = chaos.main(["audit", "127.0.0.1:1", "--format=json"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert out["ok"] is False and "error" in out
