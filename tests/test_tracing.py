"""Tracing: span lifecycle, W3C propagation, and end-to-end trace continuity through
ref → entity (TracePropagationSpec / ActorWithTracing analogs)."""

import asyncio

from surge_tpu.tracing import (
    InMemoryTracer,
    NoopTracer,
    SpanContext,
    extract_context,
    inject_context,
)


def test_inject_extract_roundtrip():
    ctx = SpanContext(trace_id="a" * 32, span_id="b" * 16)
    headers = inject_context(ctx, {"other": "x"})
    assert headers["other"] == "x"
    assert headers["traceparent"] == f"00-{'a'*32}-{'b'*16}-01"
    back = extract_context(headers)
    assert back == ctx


def test_extract_rejects_malformed():
    assert extract_context({}) is None
    assert extract_context({"traceparent": "junk"}) is None
    assert extract_context({"traceparent": "00-short-id-01"}) is None


def test_child_span_inherits_trace():
    tracer = InMemoryTracer()
    root = tracer.start_span("root")
    headers = inject_context(root.context)
    child = tracer.start_span("child", headers=headers)
    assert child.context.trace_id == root.context.trace_id
    assert child.parent_id == root.context.span_id
    assert child.context.span_id != root.context.span_id
    child.finish()
    root.finish()
    assert [s.name for s in tracer.finished] == ["child", "root"]


def test_span_events_errors_and_context_manager():
    tracer = InMemoryTracer()
    with tracer.start_span("op") as span:
        span.set_attribute("k", 1)
        span.add_event("checkpoint")
    assert tracer.finished[0].attributes["k"] == 1
    assert tracer.finished[0].status == "ok"

    try:
        with tracer.start_span("bad"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    bad = tracer.spans_named("bad")[0]
    assert bad.status == "error"
    assert bad.events[0][1] == "exception"


def test_noop_tracer_collects_nothing():
    t = NoopTracer()
    with t.start_span("x"):
        pass  # no exporter, no error


def test_head_sampling_drops_unsampled_traces():
    tracer = InMemoryTracer(sample_rate=0.5, seed=7)
    sampled = 0
    for _ in range(200):
        root = tracer.start_span("op")
        # children inherit the head decision — no per-hop coin flips
        child = tracer.start_span("child", parent=root)
        assert child.context.sampled == root.context.sampled
        child.finish()
        root.finish()
        sampled += root.context.sampled
    # only sampled spans reached the exporter, roots and children alike
    assert len(tracer.finished) == 2 * sampled
    assert 40 < sampled < 160  # probabilistic but seeded: loose bounds


def test_sampling_decision_rides_traceparent():
    tracer = InMemoryTracer(sample_rate=0.0)
    root = tracer.start_span("op")
    assert not root.context.sampled
    headers = inject_context(root.context)
    assert headers["traceparent"].endswith("-00")
    # a downstream (fully-sampling) tracer still honors the head's verdict
    downstream = InMemoryTracer(sample_rate=1.0)
    child = downstream.start_span("hop", headers=headers)
    child.finish()
    assert not child.context.sampled
    assert downstream.finished == []


def test_jsonl_exporter_roundtrip(tmp_path):
    import json

    from surge_tpu.tracing import JsonlSpanExporter, Tracer

    path = str(tmp_path / "spans.jsonl")
    with JsonlSpanExporter(path) as exporter:
        tracer = Tracer(exporter=exporter)
        with tracer.start_span("outer") as outer:
            outer.set_attribute("k", 1)
            with tracer.start_span("inner", parent=outer) as inner:
                inner.add_event("checkpoint", {"n": 2})
    lines = [json.loads(l) for l in open(path)]
    assert [r["name"] for r in lines] == ["inner", "outer"]
    assert lines[0]["parent_id"] == lines[1]["span_id"]
    assert lines[0]["trace_id"] == lines[1]["trace_id"]
    assert lines[0]["events"][0]["name"] == "checkpoint"
    assert lines[1]["attributes"] == {"k": 1}
    assert lines[0]["duration_ms"] >= 0


def test_engine_trace_continuity_ref_to_entity():
    """The ask span and the entity receive span share one trace id."""
    from surge_tpu import SurgeCommandBusinessLogic, CommandSuccess, create_engine, default_config
    from surge_tpu.models import counter

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.engine.num-partitions": 2,
    })
    tracer = InMemoryTracer()

    async def scenario():
        engine = create_engine(SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting()), config=cfg, tracer=tracer)
        await engine.start()
        r = await engine.aggregate_for("agg1").send_command(counter.Increment("agg1"))
        assert isinstance(r, CommandSuccess)
        rej = await engine.aggregate_for("agg1").send_command(
            counter.FailCommandProcessing("agg1", "no"))
        await engine.stop()

    asyncio.run(scenario())

    asks = tracer.spans_named("aggregate-ref.ProcessMessage")
    routes = tracer.spans_named("router.deliver")
    shards = tracer.spans_named("shard.deliver")
    receives = tracer.spans_named("entity.ProcessMessage")
    publishes = tracer.spans_named("publisher.publish")
    assert len(asks) == 2 and len(receives) == 2
    # continuity: every hop of command #1 rides ONE trace, parent-chained
    # ref → router → shard → entity → publisher
    tid = asks[0].context.trace_id
    assert routes[0].context.trace_id == tid
    assert routes[0].parent_id == asks[0].context.span_id
    assert shards[0].context.trace_id == tid
    assert shards[0].parent_id == routes[0].context.span_id
    assert receives[0].context.trace_id == tid
    assert receives[0].parent_id == shards[0].context.span_id
    assert receives[0].attributes["aggregate_id"] == "agg1"
    assert receives[0].status == "ok"
    # the successful command published; its publish span chains under the
    # entity receive span (the rejected command publishes nothing)
    assert publishes, "expected a publisher.publish span"
    assert publishes[0].context.trace_id == tid
    assert publishes[0].parent_id == receives[0].context.span_id


def test_tracer_none_deliver_paths_never_touch_tracing_machinery():
    """Satellite micro-assert: the per-message ``inject_context`` imports are
    hoisted to module level in router/shard, and the tracer=None hot path must
    stay a single `is None` check — if any deliver() touches the tracing
    machinery per message, this raising stand-in detonates."""
    import asyncio
    import unittest.mock as mock

    from surge_tpu.engine import router as router_mod
    from surge_tpu.engine import shard as shard_mod
    from surge_tpu.engine.entity import Envelope
    from surge_tpu.engine.partition import HostPort, PartitionTracker
    from surge_tpu.engine.router import SurgePartitionRouter

    # the hoist itself: module-level names, not per-call imports
    assert hasattr(router_mod, "inject_context")
    assert hasattr(shard_mod, "inject_context")

    def detonate(*a, **k):
        raise AssertionError("tracer=None path touched tracing machinery")

    class _Region:
        def __init__(self):
            self.delivered = []

        def deliver(self, aggregate_id, env):
            self.delivered.append(aggregate_id)

        async def stop(self):
            pass

    async def scenario():
        host = HostPort("localhost", 1)
        tracker = PartitionTracker()
        region = _Region()
        router = SurgePartitionRouter(
            num_partitions=2, tracker=tracker, local_host=host,
            region_creator=lambda p: region)
        await router.start()
        tracker.update({host: [0, 1]})
        with mock.patch.object(router_mod, "inject_context", detonate), \
                mock.patch.object(shard_mod, "inject_context", detonate):
            fut = asyncio.get_running_loop().create_future()
            router.deliver("agg-1", Envelope(message="m", reply=fut))
        assert region.delivered == ["agg-1"]
        await router.stop()

    asyncio.run(scenario())


def test_tracer_none_shard_deliver_zero_tracing_cost():
    """Same micro-assert for the Shard hop, through a real Shard."""
    import asyncio
    import unittest.mock as mock

    from surge_tpu.engine import shard as shard_mod
    from surge_tpu.engine.entity import Envelope
    from surge_tpu.engine.shard import Shard

    class _Entity:
        state_name = "running"

        def __init__(self, aggregate_id, on_passivate, on_stopped):
            self.aggregate_id = aggregate_id
            self.mail = []

        def start(self):
            pass

        def deliver(self, env):
            self.mail.append(env)

    async def scenario():
        shard = Shard("t-0", _Entity, tracer=None)

        def detonate(*a, **k):
            raise AssertionError("tracer=None path touched tracing machinery")

        with mock.patch.object(shard_mod, "inject_context", detonate):
            fut = asyncio.get_running_loop().create_future()
            shard.deliver("agg", Envelope(message="m", reply=fut))
        assert shard.live_entity("agg").mail

    asyncio.run(scenario())
