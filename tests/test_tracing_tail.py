"""Tail-based trace sampling (ISSUE 14): the per-trace buffer, the keep
decision (error / latency / breach window / mark), the bounded keep budget
and span-buffer eviction, the kept-trace ring's merge-ready envelope, and
the tracer attachment (`install_tail`)."""

import time

from surge_tpu.config import Config
from surge_tpu.metrics import engine_metrics
from surge_tpu.tracing import InMemoryTracer, Tracer
from surge_tpu.tracing.tail import TailSampler, TraceRing, install_tail


def make(latency_ms=50.0, keep_budget=64, budget_window_s=10.0,
         max_buffer_spans=4096, clock=None, metrics=None):
    ring = TraceRing(capacity=16, name="t", role="engine")
    sampler = TailSampler(ring, latency_ms=latency_ms, keep_budget=keep_budget,
                          budget_window_s=budget_window_s,
                          max_buffer_spans=max_buffer_spans,
                          metrics=metrics,
                          clock=clock or time.monotonic)
    tracer = Tracer()
    tracer.tail = sampler
    return tracer, sampler, ring


def test_fast_clean_trace_is_dropped_slow_trace_is_kept():
    tracer, sampler, ring = make(latency_ms=50.0)
    # fast + clean: sampled out at quiescence
    with tracer.start_span("fast"):
        pass
    assert len(ring) == 0
    assert sampler.dropped["sampled-out"] == 1
    # slow: the latency criterion keeps it (duration forged via end stamps)
    span = tracer.start_span("slow")
    span.start_time -= 0.2
    span.start_mono -= 0.2
    span.finish()
    assert len(ring) == 1
    entry = ring.dump()["traces"][0]
    assert entry["reason"] == "latency"
    assert entry["trace_id"] == span.context.trace_id
    assert entry["spans"][0]["name"] == "slow"
    assert sampler.kept == 1


def test_erred_trace_is_kept_with_children():
    tracer, sampler, ring = make(latency_ms=1e9)
    root = tracer.start_span("root")
    child = tracer.start_span("child", parent=root)
    child.status = "error"
    child.finish()
    # decision waits for the whole trace: nothing kept while the root is open
    assert len(ring) == 0
    root.finish()
    assert len(ring) == 1
    entry = ring.dump()["traces"][0]
    assert entry["reason"] == "error"
    assert sorted(s["name"] for s in entry["spans"]) == ["child", "root"]


def test_keep_budget_bounds_keeps_and_counts_drops():
    now = [0.0]
    tracer, sampler, ring = make(latency_ms=0.0, keep_budget=2,
                                 budget_window_s=100.0,
                                 clock=lambda: now[0])
    for _ in range(5):
        with tracer.start_span("op"):
            pass
    assert sampler.kept == 2
    assert sampler.dropped["budget"] == 3
    # window expiry restores the budget
    now[0] = 200.0
    with tracer.start_span("op"):
        pass
    assert sampler.kept == 3


def test_breach_window_and_mark_trace_keep_fast_traces():
    now = [0.0]
    tracer, sampler, ring = make(latency_ms=1e9, clock=lambda: now[0])
    with tracer.start_span("boring"):
        pass
    assert len(ring) == 0
    sampler.open_breach_window(30.0)
    with tracer.start_span("breach-adjacent"):
        pass
    assert ring.dump()["traces"][-1]["reason"] == "breach-window"
    now[0] = 100.0  # window closed again
    with tracer.start_span("later"):
        pass
    assert len(ring) == 1
    marked = tracer.start_span("exemplar")
    sampler.mark_trace(marked.context.trace_id)
    marked.finish()
    assert ring.dump()["traces"][-1]["reason"] == "marked"


def test_span_buffer_bound_evicts_oldest_in_flight_trace():
    tracer, sampler, ring = make(latency_ms=0.0, max_buffer_spans=8)
    leaked = [tracer.start_span(f"leak{i}") for i in range(12)]
    # a child finishing buffers one span per trace; roots stay open so the
    # traces never quiesce — the bound evicts the oldest instead
    for root in leaked:
        tracer.start_span("child", parent=root).finish()
    assert sampler.stats()["buffered_spans"] <= 8
    assert sampler.dropped["buffer"] >= 4


def test_head_unsampled_spans_never_reach_the_tail():
    ring = TraceRing()
    sampler = TailSampler(ring, latency_ms=0.0)
    tracer = Tracer(sample_rate=0.0)
    tracer.tail = sampler
    with tracer.start_span("unsampled"):
        pass
    assert sampler.stats()["buffered_traces"] == 0
    assert len(ring) == 0


def test_metrics_counters_ride_the_quiver():
    m = engine_metrics()
    tracer, sampler, ring = make(latency_ms=0.0, metrics=m)
    with tracer.start_span("kept"):
        pass
    values = m.registry.get_metrics()
    assert values["surge.trace.kept"] == 1.0
    assert values["surge.trace.tail-buffer-spans"] == 0.0
    sampler.latency_ms = 1e9
    with tracer.start_span("dropped"):
        pass
    assert m.registry.get_metrics()["surge.trace.dropped"] == 1.0


def test_ring_dump_envelope_is_merge_ready_and_bounded():
    ring = TraceRing(capacity=4, name="broker:1", role="broker")
    for i in range(6):
        ring.keep(f"t{i}", "latency", [{"name": "s", "trace_id": f"t{i}"}])
    dump = ring.dump()
    assert dump["recorder"] == "broker:1" and dump["role"] == "broker"
    # the mono↔wall header pair anatomy.py estimates the host offset from
    assert abs((dump["dumped_wall"] - dump["dumped_mono"])
               - (time.time() - time.monotonic())) < 1.0
    assert dump["stats"]["traces"] == 4          # bounded ring wrapped
    assert dump["stats"]["dropped"] == 2
    assert dump["stats"]["kept_total"] == 6
    assert [e["trace_id"] for e in dump["traces"]] == ["t2", "t3", "t4", "t5"]
    assert [e["trace_id"] for e in ring.dump(2)["traces"]] == ["t4", "t5"]
    assert ring.trace_ids(3) == ["t5", "t4", "t3"]  # newest first


def test_install_tail_is_config_gated_and_idempotent():
    cfg = Config(overrides={"surge.trace.ring-capacity": 8})
    tracer = InMemoryTracer()
    ring = install_tail(tracer, cfg, name="e", role="engine")
    assert ring is not None and tracer.tail is not None
    assert tracer.tail.ring is ring
    # idempotent: a second install (co-resident component) reuses the ring
    assert install_tail(tracer, cfg, name="other", role="broker") is ring
    # exporter still sees finished spans (tail rides BEHIND it, not instead)
    with tracer.start_span("op"):
        pass
    assert [s.name for s in tracer.finished] == ["op"]
    # gated off by config / by tracer=None
    off = Config(overrides={"surge.trace.tail.enabled": False})
    assert install_tail(InMemoryTracer(), off) is None
    assert install_tail(None, cfg) is None


def test_late_span_of_a_kept_trace_joins_the_ring():
    tracer, sampler, ring = make(latency_ms=0.0)
    root = tracer.start_span("root")
    root.finish()  # quiesces + keeps
    assert len(ring) == 1
    late = tracer.start_span("late", parent=root)
    late.finish()  # a pipelined retry leg finishing after the decision
    entries = ring.dump()["traces"]
    assert len(entries) == 2
    assert entries[1]["trace_id"] == root.context.trace_id
    assert entries[1]["spans"][0]["name"] == "late"
    # the late append reuses the original keep verdict, not a fresh budget slot
    assert sampler.kept == 1
