"""Incremental materialized views + changefeeds (surge_tpu.replay.views).

The streaming half of the KTable analogy: views registered against the
resident plane's refresh feed fold every committed round into per-partition
grouped-aggregate partials, and subscribers ride per-round delta changefeeds.

The load-bearing test is the golden byte-equality one: after N incremental
fold rounds — across evictions, re-admissions, a partition rebalance and a
mid-round failure re-anchor — every view must be byte-equal to a from-scratch
``scan_chunks`` over the log at the same fold watermark, on cpu AND mesh8.
The changefeed's contract rides the same bar: resume-from-watermark delivers
exactly the missed deltas (no gap, no dup), a gap beyond the delta ring (or a
failover to a fresh node) is answered with ONE reconciling snapshot, and
applying a subscriber's entries in order reconstructs the polled snapshot."""

import asyncio
import random

import numpy as np
import pytest

from surge_tpu.codec.tensor import encode_events_columnar
from surge_tpu.config import default_config
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.metrics import Metrics, engine_metrics
from surge_tpu.models import counter
from surge_tpu.replay.ledger import ReplayLedger
from surge_tpu.replay.query import Aggregate, Predicate, QueryEngine, ScanQuery
from surge_tpu.replay.resident_state import ResidentStatePlane
from surge_tpu.replay.views import MaterializedViews, ViewDef, select_top_k
from surge_tpu.serialization import SerializedMessage

EVT = counter.event_formatting()
STATE = counter.state_formatting()
TOPIC = "counter-events"
NPART = 4
SPEC = counter.make_replay_spec()

#: every aggregate op at once, keyed by aggregate id
TOTALS_Q = ScanQuery(aggregates=(Aggregate("count"),
                                 Aggregate("sum", "increment_by"),
                                 Aggregate("min", "increment_by"),
                                 Aggregate("max", "sequence_number")))
#: group-by-event-column rollup with typed pushdown + an OR group (CNF)
GROUP_Q = ScanQuery(
    aggregates=(Aggregate("count"), Aggregate("sum", "sequence_number")),
    event_types=("CountIncremented", "CountDecremented"),
    or_groups=((Predicate("increment_by", "==", 1),
                Predicate("increment_by", ">=", 3)),),
    group_by="increment_by")
#: plain count+sum view for the changefeed tests
SIMPLE_Q = ScanQuery(aggregates=(Aggregate("count"),
                                 Aggregate("sum", "increment_by")))


def part_of(agg: str) -> int:
    return int(agg.rsplit("-", 1)[1]) % NPART


def append_events(log, events):
    prod = log.transactional_producer("seed")
    prod.begin()
    for ev in events:
        msg = EVT.write_event(ev)
        prod.send(LogRecord(topic=TOPIC, partition=part_of(ev.aggregate_id),
                            key=msg.key, value=msg.value))
    prod.commit()


def make_log():
    log = InMemoryLog()
    log.create_topic(TopicSpec(TOPIC, NPART))
    return log


def make_plane_with_views(log, *, capacity=64, mesh=None, overrides=None,
                          metrics=None, flight=None, ledger=None):
    cfg = default_config().with_overrides({
        "surge.replay.resident.capacity": capacity,
        "surge.replay.resident.max-lag-records": 4096,
        "surge.replay.resident.refresh-interval-ms": 10,
        "surge.replay.batch-size": 16,
        "surge.replay.time-chunk": 8,
        "surge.query.chunk-events": 1024,
        **(overrides or {}),
    })
    plane = ResidentStatePlane(
        log, TOPIC, SPEC, config=cfg,
        deserialize_event=lambda raw: EVT.read_event(
            SerializedMessage(key="", value=raw)),
        serialize_state=lambda a, s: STATE.write_state(s).value,
        mesh=mesh, metrics=metrics, flight=flight)
    views = MaterializedViews(SPEC, config=cfg, mesh=mesh, metrics=metrics,
                              ledger=ledger, flight=flight)
    plane.attach_views(views)
    return plane, views


class EventGen:
    """Deterministic event storms over a fixed aggregate population."""

    def __init__(self, seed=0, naggs=30):
        self.rng = random.Random(seed)
        self.aggs = [f"agg-{i}" for i in range(naggs)]
        self.seqs = {a: 0 for a in self.aggs}

    def burst(self, agg, n):
        out = []
        for _ in range(n):
            self.seqs[agg] += 1
            kind = self.rng.randrange(3)
            if kind == 0:
                out.append(counter.CountIncremented(
                    agg, self.rng.randrange(1, 4), self.seqs[agg]))
            elif kind == 1:
                out.append(counter.CountDecremented(
                    agg, self.rng.randrange(1, 4), self.seqs[agg]))
            else:
                out.append(counter.NoOpEvent(agg, self.seqs[agg]))
        return out

    def storm(self, rnd, every=3, n=2):
        evs = []
        for i, a in enumerate(self.aggs):
            if (i + rnd) % every == 0:
                evs.extend(self.burst(a, n + rnd % 3))
        return evs


async def wait_caught_up(plane, timeout=20.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while plane.lag_records() > 0:
        assert asyncio.get_running_loop().time() < deadline, \
            f"refresh loop never caught up (lag {plane.lag_records()})"
        await asyncio.sleep(0.02)


async def wait_views_current(log, plane, views, names, timeout=20.0):
    """Wait until every named view's fold watermarks reach the log's end
    offsets (the plane's watermark advance and the views' leg of the round
    are separate steps — lag 0 alone doesn't mean the last fold landed)."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        await wait_caught_up(plane, timeout)
        ends = {p: log.end_offset(TOPIC, p) for p in range(NPART)}
        by_name = {v["view"]: v for v in views.summary()}
        ok = True
        for name in names:
            v = by_name[name]
            if v["error"]:
                continue
            wms = {int(p): w for p, w in v["watermarks"].items()}
            if not v["active"] or any(e and wms.get(p, 0) < e
                                      for p, e in ends.items()):
                ok = False
        if ok:
            return
        assert loop.time() < deadline, \
            f"views never caught up: {by_name} vs {ends}"
        await asyncio.sleep(0.02)


def scan_at(log, watermarks, query, *, mesh=None):
    """From-scratch reference: one batch ``scan_chunks`` over every event the
    log holds below the view's fold watermarks, served in the same canonical
    sorted-key order."""
    logs = {}
    for p_str, wm in watermarks.items():
        for rec in log.read(TOPIC, int(p_str), 0):
            if rec.offset >= wm:
                break
            ev = EVT.read_event(SerializedMessage(key="", value=rec.value))
            logs.setdefault(rec.key, []).append(ev)
    if not logs:
        return [], {}
    colev = encode_events_columnar(SPEC.registry, list(logs.values()))
    colev.aggregate_ids = list(logs)
    eng = QueryEngine(SPEC, config=default_config().with_overrides(
        {"surge.query.chunk-events": 1024}), mesh=mesh)
    res = eng.scan_chunks([colev], query)
    order = sorted(range(res.num_aggregates),
                   key=lambda j: res.aggregate_ids[j])
    return ([res.aggregate_ids[j] for j in order],
            {n: np.asarray(res.columns[n])[order] for n in res.columns})


def assert_view_golden(views, name, query, log, *, mesh=None):
    """The golden bar: snapshot byte-equal to the from-scratch scan at the
    same watermark."""
    snap = views.snapshot(name)
    assert "error" not in snap, snap
    keys, cols = scan_at(log, snap["watermarks"], query, mesh=mesh)
    assert snap["keys"] == keys, name
    assert set(snap["columns"]) == set(cols), name
    for n in cols:
        assert np.array_equal(snap["columns"][n], cols[n]), (name, n)
    return snap


def apply_entry(state, entry):
    """A subscriber's state machine: reset replaces, deltas upsert by key."""
    if entry.get("reset"):
        state.clear()
    for row in entry["rows"]:
        state[row["key"]] = row


# -- the golden acceptance test --------------------------------------------------------


def test_view_golden_across_evict_readmit_and_rebalance():
    """Views registered before the seed must stay byte-equal to a
    from-scratch scan through N fold rounds that churn the slab (capacity 8
    over 30 aggregates) and a revoke/re-grant rebalance — both the
    aggregate-id-keyed and the group-by/OR-group view."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=5)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 3)])
        registry = Metrics()
        ledger = ReplayLedger(name="engine:t")
        plane, views = make_plane_with_views(
            log, capacity=8, metrics=engine_metrics(registry), ledger=ledger)
        plane.register_view(ViewDef(name="totals", query=TOTALS_Q))
        plane.register_view(ViewDef(name="by-delta", query=GROUP_Q))
        await plane.start()
        try:
            names = ["totals", "by-delta"]
            for rnd in range(4):
                append_events(log, gen.storm(rnd))
                await wait_views_current(log, plane, views, names)
                if rnd == 1:
                    # indexer-style rebalance mid-tail: the revoke drops the
                    # views' partition-1 partials, the re-grant refolds them
                    plane.set_partitions([0, 2, 3])
                    plane.set_partitions([0, 1, 2, 3])
                    await wait_views_current(log, plane, views, names)
            assert plane.stats["evictions"] > 0, \
                "capacity 8 with 30 aggregates must have churned the slab"
            snap = assert_view_golden(views, "totals", TOTALS_Q, log)
            assert snap["keys"] == sorted(gen.aggs)
            assert_view_golden(views, "by-delta", GROUP_Q, log)
            # observability joined the round: ledger view-rounds + metrics
            assert ledger.totals["view_rounds"] > 0
            assert any(e["type"] == "view-round" for e in ledger.events())
            vals = registry.get_metrics()
            assert vals["surge.replay.views.delta-rows"] > 0
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_view_golden_mesh_sharded(mesh8):
    """The same golden bar with the plane AND the views' scans sharded over
    the 8-device mesh — view folds ride plane_mesh exactly like batch
    scans, and must equal the single-device from-scratch reference."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=9, naggs=20)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 4)])
        plane, views = make_plane_with_views(log, capacity=16, mesh=mesh8)
        plane.register_view(ViewDef(name="totals", query=TOTALS_Q))
        plane.register_view(ViewDef(name="by-delta", query=GROUP_Q))
        await plane.start()
        try:
            names = ["totals", "by-delta"]
            for rnd in range(2):
                append_events(log, gen.storm(rnd))
                await wait_views_current(log, plane, views, names)
            plane.set_partitions([0, 1, 3])  # rebalance leg on mesh too
            plane.set_partitions([0, 1, 2, 3])
            await wait_views_current(log, plane, views, names)
            assert_view_golden(views, "totals", TOTALS_Q, log)
            assert_view_golden(views, "by-delta", GROUP_Q, log)
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_view_golden_after_mid_round_failure():
    """A refresh round dying AFTER some fold groups committed re-anchors the
    polled partitions (purge + refold from 0) — the views' partials for
    those partitions must drop with the slab and refold to byte-equality,
    never double-folding an event; subscribers see a reset entry."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=11, naggs=24)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 3)])
        plane, views = make_plane_with_views(log, capacity=8)
        plane.register_view(ViewDef(name="totals", query=TOTALS_Q))
        await plane.start()
        try:
            await wait_views_current(log, plane, views, ["totals"])
            sub = views.subscribe("totals")
            real = plane._fold_group
            calls = {"n": 0}

            async def dying(group, logs, parts, gens):
                calls["n"] += 1
                if calls["n"] == 2:  # the round's SECOND group: one committed
                    raise RuntimeError("injected mid-round fold failure")
                return await real(group, logs, parts, gens)

            plane._fold_group = dying
            append_events(log, [e for a in gen.aggs
                                for e in gen.burst(a, 2)])
            deadline = asyncio.get_running_loop().time() + 10.0
            while calls["n"] < 2:
                assert asyncio.get_running_loop().time() < deadline, \
                    "injected failure never fired"
                await asyncio.sleep(0.02)
            plane._fold_group = real
            await wait_views_current(log, plane, views, ["totals"])
            assert_view_golden(views, "totals", TOTALS_Q, log)
            # the re-anchor reached the changefeed as reset entries
            entries = []
            while not sub.queue.empty():
                entries.append(sub.queue.get_nowait())
            assert entries and entries[0]["reset"] is True  # subscribe snap
            assert any(e.get("reset") for e in entries[1:]), \
                "re-anchor must publish a reconciling reset"
            # applying the whole feed reconstructs the polled snapshot
            state = {}
            for e in entries:
                apply_entry(state, e)
            snap = views.snapshot("totals")
            assert state == {r["key"]: r for r in snap["rows"]}
            views.unsubscribe(sub)
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- changefeed: resume semantics ------------------------------------------------------


def test_changefeed_resume_exact_missed_deltas_no_gap_no_dup():
    """A subscriber that disconnects mid-storm and resumes from its fold
    watermark receives exactly the missed deltas — versions strictly
    ascending past its watermark, no reset — and applying its whole entry
    stream reconstructs the same final view as polling."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=21, naggs=16)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 2)])
        plane, views = make_plane_with_views(log)
        plane.register_view(ViewDef(name="v", query=SIMPLE_Q))
        await plane.start()
        try:
            await wait_views_current(log, plane, views, ["v"])
            sub = views.subscribe("v")
            first = await asyncio.wait_for(sub.get(), 5)
            assert first["reset"] is True
            state = {}
            apply_entry(state, first)
            applied = first["version"]
            # consume part of the storm live...
            for rnd in range(3):
                append_events(log, gen.storm(rnd, every=2))
                await wait_views_current(log, plane, views, ["v"])
            while not sub.queue.empty():
                e = sub.queue.get_nowait()
                assert e["version"] > applied, "dup delta"
                apply_entry(state, e)
                applied = e["version"]
            views.unsubscribe(sub)  # ...disconnect mid-storm
            for rnd in range(3, 6):  # the storm keeps going without us
                append_events(log, gen.storm(rnd, every=2))
                await wait_views_current(log, plane, views, ["v"])
            # resume from the fold watermark: exactly the missed deltas
            sub2 = views.subscribe("v", from_version=applied)
            missed = []
            while not sub2.queue.empty():
                missed.append(sub2.queue.get_nowait())
            assert missed, "disconnected rounds must have produced deltas"
            versions = [e["version"] for e in missed]
            assert versions == sorted(set(versions)), "gap/dup in replay"
            assert all(v > applied for v in versions)
            assert not any(e.get("reset") for e in missed), \
                "an in-ring resume must replay deltas, not reconcile"
            for e in missed:
                apply_entry(state, e)
            snap = views.snapshot("v")
            assert versions[-1] == snap["version"]
            assert state == {r["key"]: r for r in snap["rows"]}, \
                "delta stream must reconstruct the polled view"
            assert_view_golden(views, "v", SIMPLE_Q, log)
            views.unsubscribe(sub2)
            assert views.subscriber_count() == 0
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_changefeed_resume_beyond_ring_reconciles_with_snapshot():
    """A resume watermark older than the delta ring cannot be replayed
    exactly — the subscriber gets ONE reconciling snapshot (reset) equal to
    the polled view, and the gap width lands on the resume-gap gauge."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=31, naggs=12)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 2)])
        registry = Metrics()
        plane, views = make_plane_with_views(
            log, metrics=engine_metrics(registry),
            overrides={"surge.replay.views.changefeed-rounds": 2})
        plane.register_view(ViewDef(name="v", query=SIMPLE_Q))
        await plane.start()
        try:
            await wait_views_current(log, plane, views, ["v"])
            for rnd in range(5):  # 5 change rounds >> ring capacity 2
                append_events(log, gen.storm(rnd, every=2))
                await wait_views_current(log, plane, views, ["v"])
            snap = views.snapshot("v")
            assert snap["version"] > 3
            sub = views.subscribe("v", from_version=1)  # long gone
            entry = sub.queue.get_nowait()
            assert entry["reset"] is True
            state = {}
            apply_entry(state, entry)
            assert state == {r["key"]: r for r in snap["rows"]}
            vals = registry.get_metrics()
            assert vals["surge.replay.views.resume-gap-rounds"] >= 1
            views.unsubscribe(sub)
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_changefeed_resume_after_kill_failover():
    """Kill-failover: the node dies, a fresh node (new plane + new views
    over the same log — the failed-over owner) seeds from scratch, and an
    old subscriber resumes with a watermark from the PREVIOUS incarnation.
    The new node's version counter restarted, so the resume is answered
    with a reconciling snapshot — byte-equal to the from-scratch scan."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=41, naggs=12)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 2)])
        plane, views = make_plane_with_views(log)
        plane.register_view(ViewDef(name="v", query=SIMPLE_Q))
        await plane.start()
        old_version = 0
        try:
            for rnd in range(4):
                append_events(log, gen.storm(rnd, every=2))
                await wait_views_current(log, plane, views, ["v"])
            old_version = views.snapshot("v")["version"]
            assert old_version > 1
        finally:
            await plane.stop()  # the kill
        # failover: the replacement owner seeds the same log from 0
        registry = Metrics()
        plane2, views2 = make_plane_with_views(
            log, metrics=engine_metrics(registry))
        plane2.register_view(ViewDef(name="v", query=SIMPLE_Q))
        await plane2.start()
        try:
            await wait_views_current(log, plane2, views2, ["v"])
            assert views2.snapshot("v")["version"] < old_version
            sub = views2.subscribe("v", from_version=old_version)
            entry = sub.queue.get_nowait()
            assert entry["reset"] is True, \
                "a from-the-future watermark must reconcile, not replay"
            state = {}
            apply_entry(state, entry)
            snap = assert_view_golden(views2, "v", SIMPLE_Q, log)
            assert state == {r["key"]: r for r in snap["rows"]}
            assert registry.get_metrics()[
                "surge.replay.views.resume-gap-rounds"] >= 1
            # post-failover the feed is live again: new rounds reach the
            # resumed subscriber as ordinary deltas
            append_events(log, gen.storm(9, every=2))
            await wait_views_current(log, plane2, views2, ["v"])
            delta = await asyncio.wait_for(sub.get(), 5)
            assert delta["reset"] is False
            apply_entry(state, delta)
            snap = views2.snapshot("v")
            assert state == {r["key"]: r for r in snap["rows"]}
            views2.unsubscribe(sub)
        finally:
            await plane2.stop()

    asyncio.run(scenario())


# -- registration lifecycle ------------------------------------------------------------


def test_register_while_running_backfills_committed_prefix():
    """A view registered on a live, seeded plane parks pending and is
    backfilled between refresh rounds — then keeps folding new rounds, and
    ends byte-equal to the from-scratch scan."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=51, naggs=16)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 3)])
        plane, views = make_plane_with_views(log)
        await plane.start()
        try:
            await wait_caught_up(plane)
            append_events(log, gen.storm(0, every=2))
            await wait_caught_up(plane)
            plane.register_view(ViewDef(name="late", query=TOTALS_Q))
            assert views.has_pending
            await wait_views_current(log, plane, views, ["late"])
            summary = views.summary()[0]
            assert summary["active"] and summary["version"] >= 1
            assert_view_golden(views, "late", TOTALS_Q, log)
            # and it now rides normal rounds like any seed-registered view
            append_events(log, gen.storm(1, every=2))
            await wait_views_current(log, plane, views, ["late"])
            assert_view_golden(views, "late", TOTALS_Q, log)
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_top_k_serving_is_exact():
    """top_k limits what the view SERVES (descending rank, ties by
    ascending key) while the full group set stays materialized — the cut
    must equal the same cut of the from-scratch reference."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=61, naggs=20)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 4)])
        plane, views = make_plane_with_views(log)
        plane.register_view(ViewDef(name="top", query=SIMPLE_Q, top_k=5,
                                    top_k_by="sum_increment_by"))
        await plane.start()
        try:
            append_events(log, gen.storm(0, every=2))
            await wait_views_current(log, plane, views, ["top"])
            snap = views.snapshot("top")
            assert len(snap["keys"]) == 5
            keys, cols = scan_at(log, snap["watermarks"], SIMPLE_Q)
            want_keys, want_cols = select_top_k(keys, cols, 5,
                                                "sum_increment_by")
            assert snap["keys"] == want_keys
            for n in want_cols:
                assert np.array_equal(snap["columns"][n], want_cols[n]), n
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_group_cap_degrades_one_view_not_the_plane():
    """A view whose group set overflows surge.replay.views.max-groups
    degrades to an error state — served as such, error entry on its feed —
    while sibling views and the plane itself keep folding."""
    async def scenario():
        log = make_log()
        gen = EventGen(seed=71, naggs=30)
        append_events(log, [e for a in gen.aggs for e in gen.burst(a, 2)])
        plane, views = make_plane_with_views(
            log, overrides={"surge.replay.views.max-groups": 4})
        plane.register_view(ViewDef(name="wide", query=TOTALS_Q))  # 30 keys
        plane.register_view(ViewDef(name="narrow", query=GROUP_Q))  # <= 4
        await plane.start()
        try:
            await wait_views_current(log, plane, views, ["narrow"])
            snap = views.snapshot("wide")
            assert "group cap exceeded" in snap["error"]
            by_name = {v["view"]: v for v in views.summary()}
            assert by_name["wide"]["error"] and not by_name["narrow"]["error"]
            assert_view_golden(views, "narrow", GROUP_Q, log)
            # the plane's own slab is untouched by the view failure
            await wait_caught_up(plane)
            assert plane.lag_records() == 0
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_registration_validation_and_unregister():
    async def scenario():
        log = make_log()
        plane, views = make_plane_with_views(log)
        with pytest.raises(ValueError):
            plane.register_view(ViewDef(name="bad", query=ScanQuery(
                aggregates=(Aggregate("sum", "no_such_column"),))))
        with pytest.raises(ValueError):
            plane.register_view(ViewDef(name="bad", query=ScanQuery(
                aggregates=(Aggregate("count"),),
                event_types=("NoSuchEvent",))))
        with pytest.raises(ValueError):
            ViewDef(name="", query=SIMPLE_Q)
        with pytest.raises(ValueError):
            ViewDef(name="v", query=SIMPLE_Q, top_k=0)
        with pytest.raises(ValueError):
            ViewDef(name="v", query=SIMPLE_Q, top_k=3, top_k_by="nope")
        vd = ViewDef(name="v", query=SIMPLE_Q, top_k=3)
        assert ViewDef.from_json(vd.as_json()) == vd
        assert vd.rank_by == "sum_increment_by"  # first non-count aggregate
        plane.register_view(vd)
        with pytest.raises(ValueError):
            plane.register_view(vd)  # duplicate name
        await plane.start()
        try:
            sub = views.subscribe("v")
            assert views.unregister("v") and not views.unregister("v")
            # the subscriber got a terminal entry; the stream is over
            await asyncio.wait_for(sub.get(), 5)  # initial snapshot
            closed = await asyncio.wait_for(sub.get(), 5)
            assert closed.get("closed") == "unregistered"
            with pytest.raises(KeyError):
                views.snapshot("v")
            with pytest.raises(KeyError):
                views.subscribe("v")
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- engine + RPC end to end -----------------------------------------------------------


def test_engine_view_rpcs_end_to_end(tmp_path):
    """The whole stack: commands through a real engine, views folding off
    its resident plane, the admin QueryView/SubscribeView RPCs, and the
    multilanguage sidecar's QueryStates/QueryView/SubscribeView twins."""
    import grpc

    from surge_tpu import SurgeCommandBusinessLogic, create_engine
    from surge_tpu.admin import AdminClient, AdminServer
    from surge_tpu.multilanguage.gateway import MultilanguageGatewayServer
    from surge_tpu.multilanguage.sdk import SerDeser, SurgeClient

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.engine.num-partitions": 2,
        "surge.replay.resident.enabled": True,
        "surge.replay.resident.refresh-interval-ms": 10,
        "surge.replay.segment-path": str(tmp_path / "counter.scol"),
    })

    async def scenario():
        engine = create_engine(SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting()), config=cfg)
        engine.register_view({"name": "totals", "query": SIMPLE_Q.as_json()})
        await engine.start()
        admin = AdminServer(engine)
        gateway = MultilanguageGatewayServer(engine)
        channel = gw_channel = None
        try:
            for i in range(6):
                ref = engine.aggregate_for(f"q-{i}")
                for _ in range(i + 1):
                    await ref.send_command(counter.Increment(f"q-{i}"))
            port = await admin.start()
            channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
            client = AdminClient(channel)

            async def poll_until(fetch, pred, timeout=15.0):
                deadline = asyncio.get_running_loop().time() + timeout
                while True:
                    payload = await fetch()
                    if pred(payload):
                        return payload
                    assert asyncio.get_running_loop().time() < deadline, \
                        f"never satisfied: {payload}"
                    await asyncio.sleep(0.05)

            snap = await poll_until(
                lambda: client.query_view("totals"),
                lambda p: len(p.get("rows", ())) == 6
                and sum(r["count"] for r in p["rows"]) == 21)
            assert snap["keys"] == [f"q-{i}" for i in range(6)]
            assert "columns" not in snap  # numpy stays in-process
            summary = await client.query_view()
            assert [v["view"] for v in summary["views"]] == ["totals"]
            assert summary["views"][0]["active"]
            with pytest.raises(RuntimeError):
                await client.query_view("no-such-view")

            # the admin changefeed: snapshot first, then a live delta
            feed = client.subscribe_view("totals")
            first = await asyncio.wait_for(feed.__anext__(), 10)
            assert first["reset"] is True
            state = {}
            apply_entry(state, first)
            await engine.aggregate_for("q-0").send_command(
                counter.Increment("q-0"))
            entry = await asyncio.wait_for(feed.__anext__(), 10)
            while not any(r["key"] == "q-0" for r in entry["rows"]):
                apply_entry(state, entry)
                entry = await asyncio.wait_for(feed.__anext__(), 10)
            apply_entry(state, entry)
            assert state["q-0"]["count"] == 2

            # register-while-running through the engine surface
            engine.register_view(ViewDef(name="late", query=TOTALS_Q))
            await poll_until(
                lambda: client.query_view(),
                lambda p: {v["view"]: v["active"] for v in p["views"]}
                == {"late": True, "totals": True})

            # the sidecar twins
            gw_port = await gateway.start()
            gw_channel = grpc.aio.insecure_channel(f"127.0.0.1:{gw_port}")
            ident = SerDeser(*([lambda b: b] * 6))
            app = SurgeClient(gw_channel, ident)
            payload = await app.query_view("totals")
            assert sum(r["count"] for r in payload["rows"]) == 22
            assert [v["view"] for v in (await app.query_view())["views"]] \
                == ["late", "totals"]
            with pytest.raises(RuntimeError):
                await app.query_view("no-such-view")
            sq = {"select": ["count"], "predicates": [
                {"column": "count", "op": ">=", "value": 4}]}
            rows = (await app.query_states(sq))["rows"]
            assert sorted(r["aggregate_id"] for r in rows) \
                == ["q-3", "q-4", "q-5"]
            # resume from the admin feed's snapshot version: the sidecar
            # replays the SAME deltas the admin feed delivered live, so
            # starting from that snapshot it reconstructs the same state
            gw_feed = app.subscribe_view("totals",
                                         from_version=first["version"])
            gw_state = {}
            apply_entry(gw_state, first)
            async for e in gw_feed:
                apply_entry(gw_state, e)
                if gw_state.get("q-0", {}).get("count") == 2:
                    break
            assert gw_state == state
        finally:
            if gw_channel is not None:
                await gw_channel.close()
            await gateway.stop()
            if channel is not None:
                await channel.close()
            await admin.stop()
            await engine.stop()

    asyncio.run(scenario())
