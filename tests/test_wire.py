"""Wire format: bit-packed windows round-trip exactly, overflow is rejected,
derived ordinal columns materialize on device, padding decodes to the sentinel.

The wire layer is what makes the 100M-event replay transfer-feasible
(SURVEY.md §7 hard-part 2); these tests pin its pack/decode contract independently of
the replay engine goldens.
"""

import numpy as np
import pytest

from surge_tpu.codec.schema import FieldSpec, SchemaRegistry
from surge_tpu.codec.wire import WireFormat
from surge_tpu.models.counter import make_registry


def _tiny_registry(bits_a=5, bits_b=None):
    from dataclasses import make_dataclass

    EvA = make_dataclass("EvA", [("a", int)])
    EvB = make_dataclass("EvB", [("b", float)])
    reg = SchemaRegistry()
    reg.register_event(EvA, fields=[FieldSpec("a", np.int32, bits=bits_a)])
    reg.register_event(EvB, fields=[FieldSpec("b", np.float32, bits=bits_b)])
    St = make_dataclass("St", [("a", int)])
    reg.register_state(St, fields=[FieldSpec("a", np.int32)])
    return reg


def test_counter_wire_is_one_byte():
    wire = WireFormat(make_registry(), {"sequence_number": "ordinal"})
    assert wire.nbytes == 1  # 3 type bits + 2 + 2 = 7 bits
    assert wire.wire_bytes_per_event() == 1
    assert [f.name for f in wire.derived_fields] == ["sequence_number"]
    # without the derivation declaration, sequence_number rides full-width
    wire2 = WireFormat(make_registry())
    assert wire2.wire_bytes_per_event() == 1 + 4


def test_pack_decode_round_trip():
    wire = WireFormat(make_registry(), {"sequence_number": "ordinal"})
    rng = np.random.default_rng(0)
    b, t = 5, 9
    type_ids = rng.integers(0, 4, size=(b, t)).astype(np.int32)
    type_ids[0, 4:] = -1  # padding tail
    cols = {
        "increment_by": rng.integers(0, 4, size=(b, t)).astype(np.int32),
        "decrement_by": rng.integers(0, 4, size=(b, t)).astype(np.int32),
    }
    packed, side = wire.pack_window(type_ids, cols, 0, t, chunk=16, bs=8)
    assert packed.shape == (16, 8, 1) and packed.dtype == np.uint8
    assert side == {}

    ev = wire.decode(packed, side, np.zeros(8, np.int32))
    got_tid = np.asarray(ev["type_id"])
    # real region round-trips; padding (both the tail and the pad rows/cols) is -1
    assert np.array_equal(got_tid[:t, :b].T, type_ids)
    assert (got_tid[t:, :] == -1).all() and (got_tid[:, b:] == -1).all()
    assert np.array_equal(np.asarray(ev["increment_by"])[:t, :b].T, cols["increment_by"])
    assert np.array_equal(np.asarray(ev["decrement_by"])[:t, :b].T, cols["decrement_by"])
    # derived ordinal: base 0 → row index + 1, at the field's dtype
    seq = np.asarray(ev["sequence_number"])
    assert seq.dtype == np.int32
    assert np.array_equal(seq[:, 0], np.arange(1, 17, dtype=np.int32))


def test_time_window_slice_and_ordinal_base():
    wire = WireFormat(make_registry(), {"sequence_number": "ordinal"})
    b, t = 3, 20
    type_ids = np.zeros((b, t), dtype=np.int32)
    cols = {"increment_by": np.ones((b, t), np.int32),
            "decrement_by": np.zeros((b, t), np.int32)}
    packed, side = wire.pack_window(type_ids, cols, 8, 16, chunk=8, bs=8)
    ev = wire.decode(packed, side, np.full(8, 8, np.int32))
    seq = np.asarray(ev["sequence_number"])
    # events at global positions 8..15 → ordinals 9..16
    assert np.array_equal(seq[:, 0], np.arange(9, 17, dtype=np.int32))


def test_overflow_raises():
    wire = WireFormat(make_registry(), {"sequence_number": "ordinal"})
    type_ids = np.zeros((1, 1), dtype=np.int32)
    cols = {"increment_by": np.array([[4]], np.int32),  # 2**2 — one past the width
            "decrement_by": np.zeros((1, 1), np.int32)}
    with pytest.raises(ValueError, match="increment_by.*2-bit"):
        wire.pack_window(type_ids, cols, 0, 1, chunk=1, bs=1)
    cols = {"increment_by": np.array([[-1]], np.int32),  # negatives cannot pack
            "decrement_by": np.zeros((1, 1), np.int32)}
    with pytest.raises(ValueError, match="increment_by"):
        wire.pack_window(type_ids, cols, 0, 1, chunk=1, bs=1)


def test_undeclared_bits_ride_side_columns():
    reg = _tiny_registry(bits_a=5, bits_b=None)
    wire = WireFormat(reg)
    assert [pf.name for pf in wire.packed_fields] == ["a"]
    assert [f.name for f in wire.side_fields] == ["b"]
    type_ids = np.array([[0, 1]], dtype=np.int32)
    cols = {"a": np.array([[17, 0]], np.int32),
            "b": np.array([[0.0, 2.5]], np.float32)}
    packed, side = wire.pack_window(type_ids, cols, 0, 2, chunk=2, bs=1)
    ev = wire.decode(packed, side, np.zeros(1, np.int32))
    assert np.asarray(ev["a"])[0, 0] == 17
    assert np.asarray(ev["b"])[1, 0] == np.float32(2.5)
    assert np.asarray(ev["b"]).dtype == np.float32


def test_unknown_derivation_rejected():
    with pytest.raises(ValueError, match="unknown derivation"):
        WireFormat(make_registry(), {"sequence_number": "fibonacci"})


def test_corrupt_type_codes_decode_as_padding():
    """Codes above num_types (possible with a corrupt word) must mask to -1, not
    dispatch to an arbitrary handler (same contract as make_step_fn's clip guard)."""
    wire = WireFormat(make_registry(), {"sequence_number": "ordinal"})
    packed = np.full((1, 1, wire.nbytes), 0xFF, dtype=np.uint8)  # type bits = 7 > 4
    ev = wire.decode(packed, {}, np.zeros(1, np.int32))
    assert int(np.asarray(ev["type_id"])[0, 0]) == -1


def test_overflow_detects_uint32_wrap():
    """Values that are multiples of 2**32 must raise, not silently wrap to 0."""
    wire = WireFormat(make_registry(), {"sequence_number": "ordinal"})
    type_ids = np.zeros((1, 1), dtype=np.int32)
    cols = {"increment_by": np.array([[2**32]], np.int64),
            "decrement_by": np.zeros((1, 1), np.int32)}
    with pytest.raises(ValueError, match="increment_by"):
        wire.pack_window(type_ids, cols, 0, 1, chunk=1, bs=1)


def test_corrupt_positive_type_id_packs_as_padding():
    """A positive out-of-range type_id must not spill into field bits: tid=8 with
    3 type bits would otherwise decode as (type 0, increment_by 1)."""
    wire = WireFormat(make_registry(), {"sequence_number": "ordinal"})
    type_ids = np.array([[8]], dtype=np.int32)
    cols = {"increment_by": np.zeros((1, 1), np.int32),
            "decrement_by": np.zeros((1, 1), np.int32)}  # tid 8 & 7 == 0 if spilled
    packed, side = wire.pack_window(type_ids, cols, 0, 1, chunk=1, bs=1)
    ev = wire.decode(packed, side, np.zeros(1, np.int32))
    assert int(np.asarray(ev["type_id"])[0, 0]) == -1
    assert int(np.asarray(ev["increment_by"])[0, 0]) == 0
