#!/usr/bin/env python
"""Bench trajectory: merge the checked-in ``BENCH_*.json`` series into one table.

Every bench round checks in another ``BENCH_*.json`` at the repo root —
headline metric files (``{"metric": ..., "value": ..., "unit": ...}``, the
paired-ladder convention), raw runner envelopes (``BENCH_rNN.json`` with
``n``/``cmd``/``rc``/``tail``), and device smoke dumps — which makes the
history write-only: nobody diffs twelve JSON files by hand. This tool reads
them ALL back and renders the trajectory::

    python tools/bench_trend.py                  # table, one row per file
    python tools/bench_trend.py --format=json    # + machine verdict LAST line

Rows are grouped per metric and ordered by round (the ``_rNN`` filename
suffix, else the payload's ``round``/``n``), with the per-round delta
against the previous round of the SAME metric — so a regression reads as a
negative delta in one glance. The summary block (and, with ``--format=json``,
the LAST stdout line, machine-readable for CI) reports first → last per
metric. Raw runner envelopes contribute their exit code (``bench_exit_code``
— a nonzero trajectory is itself a finding); files with no extractable
number still get a row (value ``-``) so the table is the complete inventory.

Exit 0 always when the scan succeeds (the table is information, not a
verdict); 2 on bad arguments / unreadable directory.
"""

import argparse
import glob
import json
import os
import re
import sys

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _extract(path: str, data) -> dict:
    """One trajectory row per file: best-effort headline metric."""
    name = os.path.basename(path)
    m = _ROUND_RE.search(name)
    rnd = int(m.group(1)) if m else None
    if rnd is None and isinstance(data, dict):
        for k in ("round", "n"):
            if isinstance(data.get(k), int):
                rnd = data[k]
                break
    row = {"file": name, "round": rnd, "metric": None, "value": None,
           "unit": ""}
    if not isinstance(data, dict):
        return row
    if isinstance(data.get("metric"), str) and \
            isinstance(data.get("value"), (int, float)):
        row["metric"] = data["metric"]
        row["value"] = data["value"]
        row["unit"] = str(data.get("unit", ""))
    elif isinstance(data.get("parsed"), dict) and \
            isinstance(data["parsed"].get("value"), (int, float)):
        row["metric"] = str(data["parsed"].get("metric", "parsed"))
        row["value"] = data["parsed"]["value"]
        row["unit"] = str(data["parsed"].get("unit", ""))
    elif isinstance(data.get("rc"), int):
        # raw runner envelope: the exit-code trajectory is the signal
        row["metric"] = "bench_exit_code"
        row["value"] = data["rc"]
        row["unit"] = "rc"
    elif isinstance(data.get("smoke"), dict):
        # device smoke dump: best steady fold rate across swept configs
        rates = [c.get("events_per_sec")
                 for c in data["smoke"].get("configs", [])
                 if isinstance(c.get("events_per_sec"), (int, float))]
        if rates:
            row["metric"] = "fold_events_per_sec"
            row["value"] = max(rates)
            row["unit"] = "events/s"
    if row["value"] is None:
        # paired-ladder notes (no headline key): peak median throughput
        # anywhere in the payload — PAIRED medians only, per BENCH_NOTES
        medians = []
        _walk_medians(data, medians)
        if medians:
            row["metric"] = "commands_per_sec_median"
            row["value"] = max(medians)
            row["unit"] = "commands/s"
    return row


def _walk_medians(node, out, key="commands_per_sec_median") -> None:
    if isinstance(node, dict):
        v = node.get(key)
        if isinstance(v, (int, float)):
            out.append(v)
        for child in node.values():
            _walk_medians(child, out, key)
    elif isinstance(node, list):
        for child in node:
            _walk_medians(child, out, key)


def collect(root: str, pattern: str = "BENCH_*.json"):
    """All rows, grouped per metric and ordered by round (trajectory order).
    Returns ``(rows, series)`` — series maps metric → first/last/delta."""
    rows = []
    for path in sorted(glob.glob(os.path.join(root, pattern))):
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as exc:
            rows.append({"file": os.path.basename(path), "round": None,
                         "metric": "unreadable", "value": None,
                         "unit": str(exc)[:80]})
            continue
        rows.append(_extract(path, data))
    rows.sort(key=lambda r: (r["metric"] or "~", r["round"] or 0, r["file"]))
    prev = {}
    for r in rows:
        r["delta_pct"] = None
        if r["metric"] and isinstance(r["value"], (int, float)):
            p = prev.get(r["metric"])
            if p:  # nonzero previous value in the same metric series
                r["delta_pct"] = round(100.0 * (r["value"] - p) / p, 1)
            prev[r["metric"]] = r["value"] or None
    series = {}
    for r in rows:
        if not r["metric"] or not isinstance(r["value"], (int, float)):
            continue
        s = series.setdefault(r["metric"], {"unit": r["unit"], "points": 0,
                                            "first": r["value"],
                                            "last": r["value"]})
        s["points"] += 1
        s["last"] = r["value"]
    for s in series.values():
        s["delta_pct"] = (round(100.0 * (s["last"] - s["first"]) / s["first"],
                                1) if s["first"] else None)
    return rows, series


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=None,
                    help="directory holding the BENCH_*.json series "
                         "(default: the repo root above tools/)")
    ap.add_argument("--glob", default="BENCH_*.json",
                    help="filename pattern to merge")
    ap.add_argument("--format", dest="fmt", choices=["text", "json"],
                    default="text",
                    help="json adds the machine-readable series summary as "
                         "the LAST stdout line")
    args = ap.parse_args(argv)
    root = args.dir or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..")
    if not os.path.isdir(root):
        print(f"not a directory: {root}", file=sys.stderr)
        return 2
    rows, series = collect(root, args.glob)
    widths = (max([len(r["metric"] or "-") for r in rows] + [6]),
              max([len(r["file"]) for r in rows] + [4]))
    print(f"{'metric':<{widths[0]}}  {'round':>5}  {'value':>14}  "
          f"{'delta':>7}  {'unit':<10}  file")
    for r in rows:
        val = (f"{r['value']:,.6g}"
               if isinstance(r["value"], (int, float)) else "-")
        delta = (f"{r['delta_pct']:+.1f}%"
                 if r["delta_pct"] is not None else "-")
        print(f"{r['metric'] or '-':<{widths[0]}}  "
              f"{r['round'] if r['round'] is not None else '-':>5}  "
              f"{val:>14}  {delta:>7}  {r['unit']:<10}  {r['file']}")
    print()
    for name, s in sorted(series.items()):
        delta = (f"{s['delta_pct']:+.1f}%"
                 if s["delta_pct"] is not None else "n/a")
        print(f"{name}: {s['first']:,.6g} -> {s['last']:,.6g} {s['unit']} "
              f"({delta} over {s['points']} points)")
    if args.fmt == "json":
        print(json.dumps({"files": len(rows), "series": series}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
