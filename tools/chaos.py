#!/usr/bin/env python
"""Chaos CLI: arm a fault plan against a RUNNING log broker and watch it.

The operator entry to the fault-injection plane (surge_tpu.testing.faults)
over the broker's admin RPCs::

    python tools/chaos.py arm 127.0.0.1:16001 flaky-network --seed 7
    python tools/chaos.py arm 127.0.0.1:16001 '{"rules": [{"site": "crash.transact.post-apply", "action": "crash"}]}'
    python tools/chaos.py status 127.0.0.1:16001
    python tools/chaos.py disarm 127.0.0.1:16001
    python tools/chaos.py broker 127.0.0.1:16001     # role/epoch/leader view
    python tools/chaos.py promote 127.0.0.1:16002    # failover drill
    python tools/chaos.py flight 127.0.0.1:16001     # full flight-recorder dump
    python tools/chaos.py metrics 127.0.0.1:16001    # broker OpenMetrics text
    python tools/chaos.py plans                      # list named plans
    python tools/chaos.py cluster 127.0.0.1:16001,127.0.0.1:16002,127.0.0.1:16003
    python tools/chaos.py cluster <t1,t2,t3> --arm flaky-network --seed 7
    python tools/chaos.py cluster <t1,t2,t3> --kill 127.0.0.1:16001
    python tools/chaos.py handoff 127.0.0.1:16001 127.0.0.1:16002
    python tools/chaos.py fleet broker@127.0.0.1:16001,engine@127.0.0.1:7001
    python tools/chaos.py fleet <specs> --serve 9464
    python tools/chaos.py replay-ledger 127.0.0.1:7001 --last 32
    python tools/chaos.py views 127.0.0.1:7001           # per-view summary
    python tools/chaos.py views 127.0.0.1:7001 totals    # one view's rows
    python tools/chaos.py sagas 127.0.0.1:7001           # saga counts + verdict
    python tools/chaos.py sagas 127.0.0.1:7001 order-17  # one saga's ledger
    python tools/chaos.py audit 127.0.0.1:7001           # consistency verdict
    python tools/chaos.py audit 127.0.0.1:7001 --format=json

``cluster`` drives N brokers from ONE invocation: with no flags it prints a
per-broker summary (role, epoch, in-sync view, per-partition high-watermarks,
quorum shape, partitions led + membership epoch, armed faults) plus the
cluster verdicts — exactly one coordinator, and under leadership spread
exactly ONE leader PER PARTITION agreed by every reachable broker; a failed
verdict exits 1 so soak harnesses and CI can gate on it. ``--arm PLAN`` arms
the same seeded plan on every broker; ``--kill ADDR`` hard-stops one of them
(the reply races the socket close — unreachable IS success).
``handoff <from> <to>`` moves the leader role deliberately (bulk slice ship
-> fence -> journal-tail ship -> dedup push -> promote -> demote) and prints
the stats, fenced-span ms included; ``--partition N`` moves just that
partition index's leadership (spread clusters). A failed handoff prints the
error and exits 1.

``arm`` takes a NAMED plan (see ``plans``) or a JSON rule list / object;
after arming it reports the plane's stats, and with ``--watch`` polls the
broker until the plan's rules are exhausted (or the broker dies — which for
crash plans is the expected outcome, reported as such).

``status`` reports the fault plane's stats PLUS the broker's flight-recorder
tail (``--tail N``, default 20) and its current replication-lag gauges, so a
chaos run is debuggable from one command without attaching a scraper.

``replay-ledger`` targets an ENGINE admin endpoint (not a broker) and dumps
its device observatory — the refresh-round ledger envelope (per-round
padding-waste / per-stage timings / gather legs, plus the roofline summary)
over the ``DumpReplayLedger`` admin RPC. Pipe it to a file and feed
``tools/roofline_record.py`` to append a roofline trajectory row.

``fleet`` federates EVERY target's OpenMetrics payload (``role@addr`` specs:
``broker@host:port`` over the log-service GetMetricsText RPC,
``engine@host:port`` over the admin RPC, ``role@http://...`` plain HTTP)
into one instance/role-labelled exposition on stdout — or keeps serving it
from a scrape port with ``--serve PORT`` (0 = ephemeral; Ctrl-C stops). The
live table view over the same pass is ``tools/surgetop.py``.

Exit code 0 on success; 1 when a verdict fails (``cluster`` with a
leadership violation, ``handoff`` refused/failed); 3 when --watch ends with
the broker unreachable (crash plans: that IS the outcome); 2 on bad
arguments.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("command",
                    choices=["arm", "disarm", "status", "broker", "promote",
                             "flight", "metrics", "plans", "cluster",
                             "handoff", "fleet", "replay-ledger", "views",
                             "sagas", "audit"])
    ap.add_argument("target", nargs="?",
                    help="broker host:port (cluster: comma-separated list; "
                         "handoff: the FROM broker)")
    ap.add_argument("plan", nargs="?",
                    help="named fault plan or JSON rules (arm only); the TO "
                         "broker (handoff only)")
    ap.add_argument("--seed", type=int, default=0,
                    help="deterministic schedule seed (arm only)")
    ap.add_argument("--arm", dest="cluster_arm", default=None,
                    help="cluster: arm this plan on every broker")
    ap.add_argument("--kill", dest="cluster_kill", default=None,
                    help="cluster: hard-stop this broker (host:port)")
    ap.add_argument("--watch", action="store_true",
                    help="after arming, poll until every rule is exhausted "
                         "or the broker goes down")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="--watch poll interval seconds")
    ap.add_argument("--tail", type=int, default=20,
                    help="flight-recorder events shown by status")
    ap.add_argument("--serve", type=int, default=None, metavar="PORT",
                    help="fleet: serve the merged exposition from this "
                         "scrape port (0 = ephemeral) instead of printing "
                         "one pass")
    ap.add_argument("--partition", type=int, default=None,
                    help="handoff: move only this partition index's "
                         "leadership (spread clusters)")
    ap.add_argument("--last", type=int, default=None,
                    help="replay-ledger: newest N ledger rounds")
    ap.add_argument("--format", dest="fmt", choices=["text", "json"],
                    default="text",
                    help="audit: text panel, or json with the machine-"
                         "readable verdict as the LAST stdout line")
    args = ap.parse_args(argv)

    if args.command == "plans":
        from surge_tpu.testing.faults import NAMED_PLANS

        for name, factory in sorted(NAMED_PLANS.items()):
            rules = [r.as_dict() for r in factory()]
            print(f"{name}: {json.dumps(rules)}")
        return 0

    if not args.target:
        print("a broker target is required", file=sys.stderr)
        return 2

    from surge_tpu.log import GrpcLogTransport

    if args.command == "replay-ledger":
        return _replay_ledger(args)
    if args.command == "views":
        return _views(args)
    if args.command == "sagas":
        return _sagas(args)
    if args.command == "audit":
        return _audit(args)
    if args.command == "fleet":
        return _fleet(args)
    if args.command == "cluster":
        return _cluster(args)
    if args.command == "handoff":
        if not args.plan:
            print("handoff needs <from> <to>", file=sys.stderr)
            return 2
        client = GrpcLogTransport(args.target)
        try:
            if args.partition is not None:
                stats = client.cluster_handoff(args.plan, args.partition)
            else:
                stats = client.handoff_partition(args.plan)
            print(json.dumps(stats, indent=2))
            return 0
        except Exception as exc:  # noqa: BLE001 — a failed handoff must gate
            print(json.dumps({"verdict": "FAILED",
                              "error": str(exc)[:500]}, indent=2))
            return 1
        finally:
            client.close()

    client = GrpcLogTransport(args.target)
    try:
        if args.command == "broker":
            print(json.dumps(client.broker_status(), indent=2))
            return 0
        if args.command == "promote":
            print(json.dumps(client.promote_follower(), indent=2))
            return 0
        if args.command == "flight":
            print(json.dumps(client.flight_dump(), indent=2))
            return 0
        if args.command == "metrics":
            print(client.log_metrics_text(), end="")
            return 0
        if args.command == "status":
            # one debuggable view: plane stats + the black-box tail + the
            # replication-lag gauges, no scraper required
            out = dict(client.fault_stats())
            try:
                # native-path health: a silently-degraded broker (stale .so
                # -> Python fallback) is visible at a glance
                out["native"] = client.broker_status().get(
                    "native", "unavailable")
            except Exception as exc:  # noqa: BLE001 — older broker
                out["native"] = f"unavailable: {exc!r}"
            try:
                out["flight_tail"] = client.flight_dump(
                    last=args.tail)["events"]
            except Exception as exc:  # noqa: BLE001 — older broker
                out["flight_tail"] = f"unavailable: {exc!r}"
            try:
                out["replication_lag"] = [
                    line for line in client.log_metrics_text().splitlines()
                    if line.startswith(("surge_log_replication_lag",
                                        "surge_log_replication_in_sync"))]
            except Exception as exc:  # noqa: BLE001 — older broker
                out["replication_lag"] = f"unavailable: {exc!r}"
            print(json.dumps(out, indent=2))
            return 0
        if args.command == "disarm":
            print(json.dumps(client.disarm_faults(), indent=2))
            return 0
        # arm
        if not args.plan:
            print("arm needs a named plan or JSON rules "
                  "(see `chaos.py plans`)", file=sys.stderr)
            return 2
        stats = client.arm_faults(args.plan, seed=args.seed)
        print(json.dumps(stats, indent=2))
        if not args.watch:
            return 0
        while True:
            time.sleep(args.interval)
            try:
                stats = client.fault_stats()
            except Exception as exc:  # noqa: BLE001 — broker gone
                print(json.dumps({"outcome": "broker unreachable "
                                             "(crash plans: expected)",
                                  "error": str(exc)[:200]}))
                return 3
            exhausted = all(r["times"] is not None
                            and r["fired"] >= r["times"]
                            for r in stats["rules"])
            print(json.dumps({"injected": stats["injected"],
                              "crashed": stats["crashed"],
                              "exhausted": exhausted}))
            if exhausted or stats["crashed"]:
                print(json.dumps({"outcome": "plan complete", **stats}))
                return 0
    finally:
        client.close()


def _render_bucket_anatomy(payload) -> str:
    """Per-round bucket fill + waste columns off a ledger envelope (ISSUE
    18's bucketed ragged dispatch): one line per round with buckets, then
    one line per bucket program (`w<width>×<lanes_b>` lanes dealt / lane
    slots, slot fill, waste, ragged-tile flag). Empty string when no round
    in the dump carried bucket anatomy (dense or pre-bucketing engines)."""
    lines = []
    for ev in payload.get("events", []):
        if ev.get("type") != "round" or not ev.get("buckets"):
            continue
        lines.append(
            f"round events={ev['events']} lanes={ev['lanes']} "
            f"waste={ev.get('waste')} bucket_table={ev.get('bucket_table')}")
        for bk in ev["buckets"]:
            lanes, lanes_b = bk.get("lanes", 0), bk.get("lanes_b", 0)
            disp, occ = bk.get("dispatched", 0), bk.get("occupied", 0)
            lines.append(
                f"  w{bk.get('width')}×{lanes_b}: lanes {lanes}/{lanes_b}"
                f" fill={occ / disp:.2f}" if disp else
                f"  w{bk.get('width')}×{lanes_b}: lanes {lanes}/{lanes_b}"
                f" fill=-")
            if disp:
                lines[-1] += (f" waste={disp / occ:.2f}" if occ
                              else " waste=-")
                if bk.get("ragged"):
                    lines[-1] += " ragged"
    return "\n".join(lines)


def _replay_ledger(args) -> int:
    """Device-observatory dump from the CLI: one ``DumpReplayLedger``
    envelope (refresh rounds + roofline summary) off an ENGINE admin
    endpoint, printed as JSON — a down/observatory-less engine is a
    reported finding, exit 1. Rounds that carried bucket anatomy (the
    bucketed ragged dispatch) additionally render a per-bucket fill/waste
    table on STDERR, keeping stdout the parseable envelope."""
    import asyncio

    import grpc

    from surge_tpu.admin.server import AdminClient

    async def fetch():
        async with grpc.aio.insecure_channel(args.target) as channel:
            return await AdminClient(channel).replay_ledger_dump(args.last)

    try:
        payload = asyncio.run(fetch())
        print(json.dumps(payload, indent=2))
        anatomy = _render_bucket_anatomy(payload)
        if anatomy:
            print(anatomy, file=sys.stderr)
        return 0
    except Exception as exc:  # noqa: BLE001 — a down engine is the finding
        print(json.dumps({"error": str(exc)[:500]}, indent=2))
        return 1


def _views(args) -> int:
    """Materialized-view operator panel off an ENGINE admin endpoint: the
    per-view ``QueryView`` summary (active/version, fold watermarks, group
    and subscriber counts, degraded-state errors) — or, with a view name as
    the second positional, that one view's served snapshot rows."""
    import asyncio

    import grpc

    from surge_tpu.admin.server import AdminClient

    async def fetch():
        async with grpc.aio.insecure_channel(args.target) as channel:
            return await AdminClient(channel).query_view(args.plan or "")

    try:
        payload = asyncio.run(fetch())
        print(json.dumps(payload, indent=2))
        return 0
    except Exception as exc:  # noqa: BLE001 — a down engine is the finding
        print(json.dumps({"error": str(exc)[:500]}, indent=2))
        return 1


def _sagas(args) -> int:
    """Saga operator panel off an ENGINE admin endpoint: the fleet summary
    (per-status counts, in-flight/dead-letter totals, drivers) PLUS the
    ledger-reconciliation verdict — every terminal saga must be all-steps-
    committed XOR all-committed-steps-compensated. A violated invariant (or
    a summary that reports not-ok) exits 1 so chaos harnesses and CI can
    gate on it; with a saga id as the second positional the panel shows that
    one saga's ledger instead (committed/compensated steps, attempts,
    driver liveness) and exits 0 whenever the saga is known."""
    import asyncio

    import grpc

    from surge_tpu.admin.server import AdminClient

    async def fetch():
        async with grpc.aio.insecure_channel(args.target) as channel:
            return await AdminClient(channel).saga_status(args.plan or "")

    try:
        payload = asyncio.run(fetch())
    except Exception as exc:  # noqa: BLE001 — a down engine is the finding
        print(json.dumps({"error": str(exc)[:500]}, indent=2))
        return 1
    print(json.dumps(payload, indent=2))
    if args.plan:  # one saga's ledger
        return 0 if payload.get("status") != "unknown" else 1
    return 0 if payload.get("ok") else 1


def _audit(args) -> int:
    """Consistency-observatory verdict off an ENGINE admin endpoint: the
    auditor's unresolved-divergence ledger (shadow-replay mismatches name
    the aggregate + differing fields, digest mismatches the partition + each
    replica's CRC, dedup holes the probe) plus cycle stats and the last
    round's detail. ANY unresolved divergence exits 1 — the same verdict
    convention as ``cluster``/``handoff``/``sagas``, so chaos harnesses and
    CI gate on it. ``--format=json`` prints the full payload with the
    machine-readable verdict as the LAST stdout line."""
    import asyncio

    import grpc

    from surge_tpu.admin.server import AdminClient

    async def fetch():
        async with grpc.aio.insecure_channel(args.target) as channel:
            return await AdminClient(channel).audit_status()

    try:
        payload = asyncio.run(fetch())
    except Exception as exc:  # noqa: BLE001 — a down engine is the finding
        print(json.dumps({"ok": False, "error": str(exc)[:500]}))
        return 1
    if args.fmt == "json":
        # full detail first, one-line verdict LAST (machine-readable tail)
        print(json.dumps(payload, indent=2))
        print(json.dumps({"ok": payload.get("ok", False),
                          "unresolved": payload.get("unresolved", [])}))
        return 0 if payload.get("ok") else 1
    stats = payload.get("stats", {})
    print(f"consistency audit: {'OK' if payload.get('ok') else 'DIVERGED'} "
          f"(cycles={stats.get('cycles', 0)} "
          f"rows={stats.get('cohort_rows', 0)} "
          f"divergent={stats.get('divergent_rows', 0)} "
          f"digest_mismatches={stats.get('digest_mismatches', 0)} "
          f"dedup_holes={stats.get('dedup_holes', 0)})")
    for item in payload.get("unresolved", []):
        print(f"  UNRESOLVED {':'.join(item.get('key', []))}: "
              f"{json.dumps({k: v for k, v in item.items() if k != 'key'})}")
    return 0 if payload.get("ok") else 1


def _fleet(args) -> int:
    """Federated scrape from the CLI: one merged, instance/role-labelled
    OpenMetrics exposition over every ``role@addr`` target — printed once,
    or served continuously from the scraper's own scrape port."""
    from surge_tpu.observability import FederatedScraper

    specs = [t.strip() for t in args.target.split(",") if t.strip()]
    if not specs:
        print("fleet needs role@addr specs", file=sys.stderr)
        return 2
    scraper = FederatedScraper(specs)
    try:
        if args.serve is None:
            print(scraper.scrape_and_render(), end="")
            return 0
        port = scraper.serve(port=args.serve)
        print(f"serving federated scrape on http://127.0.0.1:{port}/metrics "
              f"({len(specs)} targets); Ctrl-C stops", file=sys.stderr)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            return 0
    finally:
        scraper.stop()


def _cluster(args) -> int:
    """One invocation across N brokers: arm / kill / summarize. The summary
    is the quorum-plane debugging view — per-broker role+epoch+hwm (why a
    follower read is or is not servable) and a cluster-level verdict that
    exactly one broker is leading."""
    from surge_tpu.log import GrpcLogTransport

    targets = [t.strip() for t in args.target.split(",") if t.strip()]
    if len(targets) < 2:
        print("cluster needs a comma-separated broker list", file=sys.stderr)
        return 2
    out = {"brokers": {}, "leaders": []}
    rc = 0
    partition_claims = {}  # partition index -> [brokers claiming leadership]
    assignment_views = {}  # target -> (assign_epoch, frozen assignment map)
    for target in targets:
        client = GrpcLogTransport(target)
        try:
            if args.cluster_kill == target:
                client.kill_broker()
                out["brokers"][target] = {"killed": True}
                continue
            if args.cluster_arm:
                client.arm_faults(args.cluster_arm, seed=args.seed)
            status = client.broker_status()
            row = {
                "role": status["role"],
                "epoch": status["epoch"],
                "leader_hint": status.get("leader_hint", ""),
                "high_watermarks": status.get("high_watermarks", {}),
                "quorum": status.get("quorum", {}),
                # per-partition leadership spread (ISSUE 13): what this
                # broker leads and which membership/assignment record
                # version it is operating under
                "partitions_led": status.get("partitions_led", []),
                "membership": status.get("membership", {}),
                "assign_epoch": status.get("assign_epoch", 0),
                "handoff_fence": status.get("handoff_fence", False),
                "catch_up": status.get("catch_up", {}),
                "native": status.get("native", {}),
            }
            for p in status.get("partitions_led", []):
                partition_claims.setdefault(int(p), []).append(target)
            if status.get("assignments"):
                assignment_views[target] = (
                    status.get("assign_epoch", 0),
                    tuple(sorted(status["assignments"].items())))
            try:
                row["faults"] = client.fault_stats()
            except Exception as exc:  # noqa: BLE001 — older broker
                row["faults"] = f"unavailable: {exc!r}"
            if status["role"] == "leader":
                out["leaders"].append(target)
                try:
                    row["replication"] = client.replication_status()
                except Exception:  # noqa: BLE001
                    pass
            out["brokers"][target] = row
        except Exception as exc:  # noqa: BLE001 — broker down: report, go on
            out["brokers"][target] = {"unreachable": str(exc)[:200]}
        finally:
            client.close()
    problems = []
    if len(out["leaders"]) != 1:
        problems.append(f"{len(out['leaders'])} coordinators")
    if assignment_views:
        out["partition_leaders"] = {str(p): owners for p, owners
                                    in sorted(partition_claims.items())}
        for p, owners in sorted(partition_claims.items()):
            if len(owners) != 1:
                problems.append(
                    f"partition {p}: {len(owners)} leaders {sorted(owners)}")
        newest = max(epoch for epoch, _m in assignment_views.values())
        maps = {m for epoch, m in assignment_views.values()
                if epoch == newest}
        if len(maps) > 1:
            problems.append("brokers at the newest assign epoch disagree "
                            "on the partition map")
        all_assigned = {int(k) for _e, m in assignment_views.values()
                        for k, _v in m}
        for p in sorted(all_assigned - set(partition_claims)):
            problems.append(f"partition {p}: no live leader")
    out["verdict"] = ("ok: exactly one leader"
                      + (" per partition" if assignment_views else "")
                      if not problems else
                      "DEGRADED: " + "; ".join(problems))
    if problems:
        rc = 1  # soak harnesses / CI gate on this (ISSUE 13 satellite)
    if args.cluster_kill and args.cluster_kill not in targets:
        print(f"--kill target {args.cluster_kill} not in the cluster list",
              file=sys.stderr)
        rc = 2
    print(json.dumps(out, indent=2))
    return rc


if __name__ == "__main__":
    sys.exit(main())
