#!/usr/bin/env python
"""Offline FileLog compaction: rewrite a log root's compacted topics in place.

The operator-side entry to surge_tpu.log.compactor — compact a cold (or live:
the swap is crash-safe and readers retry) FileLog root without an engine,
printing per-partition stats and total bytes reclaimed::

    python tools/compact_log.py /var/lib/surge/log
    python tools/compact_log.py /var/lib/surge/log --topic counter-state --json
    python tools/compact_log.py /var/lib/surge/log --tombstone-retention-ms 0

Exit code 0 on success; 2 when the root holds no compacted topics.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", help="FileLog root directory")
    ap.add_argument("--topic", action="append", default=None,
                    help="compact only this topic (repeatable; default: every "
                         "compacted topic in the root)")
    ap.add_argument("--tombstone-retention-ms", type=float, default=60_000.0,
                    help="drop tombstones older than this (default 60s; 0 = "
                         "GC every tombstone immediately)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of the table")
    args = ap.parse_args(argv)

    from surge_tpu.log import FileLog

    log = FileLog(args.root)
    try:
        names = (args.topic if args.topic
                 else sorted(t.name for t in log._topics.values()
                             if t.compacted))
        all_stats = []
        for name in names:
            spec = log._topics.get(name)  # non-mutating: no typo auto-create
            if spec is None:
                print(f"skipping {name!r}: no such topic", file=sys.stderr)
                continue
            if not spec.compacted:
                print(f"skipping {name!r}: not a compacted topic",
                      file=sys.stderr)
                continue
            for p in range(spec.partitions):
                all_stats.append(log.compact_partition(
                    name, p,
                    tombstone_retention_s=args.tombstone_retention_ms / 1000.0))
        if not all_stats:
            print("no compacted topics found", file=sys.stderr)
            return 2
        reclaimed = sum(s.bytes_reclaimed for s in all_stats)
        dropped = sum(s.records_dropped for s in all_stats)
        if args.json:
            print(json.dumps({
                "partitions": [s.as_dict() for s in all_stats],
                "bytes_reclaimed": reclaimed, "records_dropped": dropped}))
        else:
            for s in all_stats:
                print(f"{s.topic}[{s.partition}]: {s.records_before} -> "
                      f"{s.records_after} records, "
                      f"{s.bytes_reclaimed} bytes reclaimed "
                      f"({s.tombstones_dropped} tombstones GC'd, "
                      f"{s.duration_s * 1000:.1f} ms)")
            print(f"total: {reclaimed} bytes reclaimed, "
                  f"{dropped} records dropped")
        return 0
    finally:
        log.close()


if __name__ == "__main__":
    sys.exit(main())
