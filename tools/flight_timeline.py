#!/usr/bin/env python
"""Merge flight-recorder dumps into one ordered incident timeline.

Dumps come from ``tools/chaos.py flight <broker>`` (live), from the broker's
crash auto-dump files (``surge.log.flight.dump-dir``), from the ENGINE admin
RPC (``AdminClient.flight_dump()``), or from ``SURGE_BENCH_FAILOVER=1``'s
payload. Each dump is the JSON envelope
:meth:`surge_tpu.observability.FlightRecorder.dump` writes::

    python tools/flight_timeline.py leader.json follower.json
    python tools/chaos.py flight 127.0.0.1:16001 > l.json
    python tools/chaos.py flight 127.0.0.1:16002 > f.json
    python tools/flight_timeline.py l.json f.json --json
    python tools/flight_timeline.py l.json f.json --engine engine.json

``--engine FILE`` (repeatable) adds an ENGINE-lane dump: its events —
publisher lane transitions, rebalance fan-out, resident-plane moves,
health-bus restarts, SLO breaches — interleave with the broker events so one
timeline shows the broker kill AND the engine-side fence/rejoin it caused.
(Engine dumps pulled over the admin RPC already carry ``role: engine`` and
need no flag; the flag force-tags hand-saved files.)

Output: the merged, time-ordered event stream (monotonic ordering when every
dump came from one host — CLOCK_MONOTONIC is host-shared and NTP-step-proof —
wall-clock ordering otherwise), each line tagged with its lane, followed by
the reconstructed failover phases: promotion decision → promotion → fence →
truncation → first acked post-failover commit (docs/operations.md "reading a
failover timeline"). An engine-lane-only input yields the merged stream with
all phases missing — reported, not raised.

Exit code 0 when the reconstruction is complete, 1 when phases are missing
(still prints what it found), 2 on bad input.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def _fmt_event(ev: dict, t0: float, key: str) -> str:
    extras = {k: v for k, v in ev.items()
              if k not in ("seq", "mono", "wall", "type", "recorder", "lane")}
    extra = (" " + json.dumps(extras, sort_keys=True)) if extras else ""
    lane = ev.get("lane", "broker")
    return (f"+{(ev.get(key, 0.0) - t0) * 1000.0:10.1f}ms "
            f"[{lane:>6s}] {ev.get('recorder', '?'):>21s}  "
            f"{ev['type']}{extra}")


def _load(path: str):
    with open(path) as f:
        return json.load(f)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dumps", nargs="+", help="flight dump JSON files")
    ap.add_argument("--engine", action="append", default=[],
                    metavar="FILE",
                    help="engine-lane dump file (repeatable); events are "
                         "tagged [engine] on the merged timeline")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged timeline + phases as one JSON "
                         "object instead of the human view")
    args = ap.parse_args(argv)

    from surge_tpu.observability import (
        merge_dumps,
        reconstruct_failover,
        same_clock_domain,
    )

    dumps = []
    try:
        for path in args.dumps:
            dumps.append(_load(path))
        for path in args.engine:
            dump = _load(path)
            dump["role"] = "engine"  # force-tag hand-saved files
            dumps.append(dump)
    except (OSError, ValueError) as exc:
        print(f"cannot read dump {path}: {exc}", file=sys.stderr)
        return 2

    merged = merge_dumps(dumps)
    recon = reconstruct_failover(merged)
    if args.json:
        print(json.dumps({"events": merged, **recon}, indent=2))
        return 0 if recon["complete"] else 1

    if not merged:
        print("no events in any dump")
        return 1
    # offsets must use the SAME key the merge ordered by: monotonic stamps
    # from different hosts are incomparable and would print garbage offsets
    key = "mono" if same_clock_domain(dumps) else "wall"
    t0 = merged[0].get(key, 0.0)
    lanes = sorted({e.get("lane", "broker") for e in merged})
    print(f"merged timeline ({len(merged)} events from "
          f"{len(dumps)} dumps; lanes: {', '.join(lanes)}"
          + ("" if key == "mono"
             else "; cross-host: wall-clock ordering") + "):")
    for ev in merged:
        print(" ", _fmt_event(ev, t0, key))
    print("\nfailover phases:")
    for name, ev in recon["phases"].items():
        if ev is None:
            print(f"  {name:22s} MISSING")
        else:
            print(f"  {name:22s} {_fmt_event(ev, t0, key)}")
    if recon["span_ms"] is not None:
        print(f"\ndecision -> first ack: {recon['span_ms']}ms")
    print("reconstruction complete" if recon["complete"]
          else "reconstruction INCOMPLETE")
    return 0 if recon["complete"] else 1


if __name__ == "__main__":
    sys.exit(main())
