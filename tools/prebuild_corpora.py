#!/usr/bin/env python
"""Pre-build the on-chip sweep's corpora on the host CPU.

Run this BEFORE the TPU retry loop so a successful tunnel claim spends its
window measuring, not synthesizing: the smoke cache (50k/5M) and the full
corpus (1M/100M, with packed wire) land on disk and `onchip_sweep.run_sweep`
finds both via its crash-safe markers.
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# host-only: never touch the tunneled backend from this process
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.pop("AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

SMOKE = os.environ.get("SURGE_ONCHIP_CACHE", "/tmp/corpus_smoke5m")
FULL = os.environ.get("SURGE_ONCHIP_FULL", "/tmp/corpus_full100m")


if __name__ == "__main__":
    from onchip_sweep import ensure_corpus_cache

    t0 = time.perf_counter()
    ensure_corpus_cache(SMOKE, 50_000, 5_000_000, seed=43)
    print(f"smoke cache ready: {SMOKE} ({time.perf_counter() - t0:.1f}s)",
          flush=True)
    t0 = time.perf_counter()
    # seed 42 = bench.py main's corpus, so sweep results are comparable
    ensure_corpus_cache(FULL, 1_000_000, 100_000_000, seed=42)
    print(f"full cache ready: {FULL} ({time.perf_counter() - t0:.1f}s)",
          flush=True)
    print("prebuild done", flush=True)
