#!/usr/bin/env python
"""Regenerate the OpenMetrics goldens from the canonical recording sequences:
tests/golden/metrics.om (engine registry), tests/golden/metrics_broker.om
(broker registry), and tests/golden/metrics_fleet.om (the MERGED federated
payload over canned engine+broker targets — instance/role labels, up and
staleness gauges, fleet self-instruments).

Run after an intentional change to the exposition format, any predeclared
instrument set, or the federation merge, then update the docs/observability.md
catalogs to match — golden and catalog are COUPLED (tests/test_exposition.py
and surgelint's metric-catalog rule enforce both); regen all together."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

from surge_tpu.metrics.exposition import render_openmetrics  # noqa: E402
from test_exposition import (  # noqa: E402
    BROKER_GOLDEN_PATH,
    GOLDEN_PATH,
    golden_broker_metrics,
    golden_engine_metrics,
)
from test_federation import FLEET_GOLDEN_PATH, golden_fleet_scrape  # noqa: E402

for path, text in (
        (GOLDEN_PATH, render_openmetrics(golden_engine_metrics().registry)),
        (BROKER_GOLDEN_PATH,
         render_openmetrics(golden_broker_metrics().registry)),
        (FLEET_GOLDEN_PATH, golden_fleet_scrape().render())):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    print(f"wrote {path} ({len(text.splitlines())} lines)")
