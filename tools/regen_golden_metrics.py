#!/usr/bin/env python
"""Regenerate the OpenMetrics goldens from the canonical recording sequences:
tests/golden/metrics.om (engine registry), tests/golden/metrics_broker.om
(broker registry), and tests/golden/metrics_fleet.om (the MERGED federated
payload over canned engine+broker targets — instance/role labels, up and
staleness gauges, fleet self-instruments).

Run after an intentional change to the exposition format, any predeclared
instrument set, or the federation merge, then update the docs/observability.md
catalogs to match — golden and catalog are COUPLED (tests/test_exposition.py
and surgelint's metric-catalog rule enforce both); regen all together.

``--check`` verifies WITHOUT writing: renders all three payloads, diffs them
against the checked-in goldens, and exits 1 naming every drifted file (with
the first differing line) — the CI gate that catches a stale golden the day
an instrument changes, not the week someone remembers to regen."""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))


def _renders():
    from surge_tpu.metrics.exposition import render_openmetrics
    from test_exposition import (
        BROKER_GOLDEN_PATH,
        GOLDEN_PATH,
        golden_broker_metrics,
        golden_engine_metrics,
    )
    from test_federation import FLEET_GOLDEN_PATH, golden_fleet_scrape

    return (
        (GOLDEN_PATH, render_openmetrics(golden_engine_metrics().registry)),
        (BROKER_GOLDEN_PATH,
         render_openmetrics(golden_broker_metrics().registry)),
        (FLEET_GOLDEN_PATH, golden_fleet_scrape().render()))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="verify the checked-in goldens match the canonical "
                         "renders; exit 1 on any drift, write nothing")
    args = ap.parse_args(argv)
    drifted = []
    for path, text in _renders():
        if args.check:
            try:
                with open(path, encoding="utf-8") as f:
                    on_disk = f.read()
            except OSError:
                on_disk = None
            if on_disk == text:
                print(f"ok {path}")
                continue
            drifted.append(path)
            if on_disk is None:
                print(f"DRIFT {path}: golden missing")
                continue
            want, got = text.splitlines(), on_disk.splitlines()
            for i, (w, g) in enumerate(zip(want, got), start=1):
                if w != g:
                    print(f"DRIFT {path}: line {i}\n  golden: {g}\n"
                          f"  render: {w}")
                    break
            else:
                print(f"DRIFT {path}: line count {len(got)} on disk vs "
                      f"{len(want)} rendered")
        else:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                f.write(text)
            print(f"wrote {path} ({len(text.splitlines())} lines)")
    if drifted:
        print(f"{len(drifted)} golden(s) drifted — run "
              f"tools/regen_golden_metrics.py to refresh (and sync the "
              f"docs/observability.md catalogs)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
