#!/usr/bin/env python
"""Regenerate tests/golden/metrics.om from the canonical recording sequence.

Run after an intentional change to the exposition format or the predeclared
EngineMetrics instrument set, then update the docs/observability.md catalog to
match (tests/test_exposition.py enforces both)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

from surge_tpu.metrics.exposition import render_openmetrics  # noqa: E402
from test_exposition import GOLDEN_PATH, golden_engine_metrics  # noqa: E402

os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
text = render_openmetrics(golden_engine_metrics().registry)
with open(GOLDEN_PATH, "w") as f:
    f.write(text)
print(f"wrote {GOLDEN_PATH} ({len(text.splitlines())} lines)")
