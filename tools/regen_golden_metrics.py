#!/usr/bin/env python
"""Regenerate the OpenMetrics goldens from the canonical recording sequences:
tests/golden/metrics.om (engine registry) and tests/golden/metrics_broker.om
(broker registry).

Run after an intentional change to the exposition format or either
predeclared instrument set, then update the docs/observability.md catalogs to
match — golden and catalog are COUPLED (tests/test_exposition.py enforces
both); regen both together."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

from surge_tpu.metrics.exposition import render_openmetrics  # noqa: E402
from test_exposition import (  # noqa: E402
    BROKER_GOLDEN_PATH,
    GOLDEN_PATH,
    golden_broker_metrics,
    golden_engine_metrics,
)

for path, quiver in ((GOLDEN_PATH, golden_engine_metrics()),
                     (BROKER_GOLDEN_PATH, golden_broker_metrics())):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    text = render_openmetrics(quiver.registry)
    with open(path, "w") as f:
        f.write(text)
    print(f"wrote {path} ({len(text.splitlines())} lines)")
