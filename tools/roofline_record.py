#!/usr/bin/env python
"""roofline_record — snapshot a refresh-round ledger into the roofline JSONL.

Pulls the device observatory's ``DumpReplayLedger`` envelope from a live
engine admin endpoint (or reads one saved earlier as JSON), extracts the
roofline summary (measured fold ev/s, µs/slot, µs/event, padding-waste
ratio) and appends ONE JSON line to the trajectory file — append-only, so
the file accumulates the machine's measured history across runs and a
regression shows as a row, not a reverted doc table (docs/roofline.md)::

    python tools/roofline_record.py --engine 127.0.0.1:7001 \
        --out roofline.jsonl --note "post PR-16"
    python tools/roofline_record.py ledger_dump.json --out roofline.jsonl
    python tools/roofline_record.py ledger_dump.json --out roofline.jsonl \
        --compare steady-ragged-cpu

``--compare`` prints measured/published ratios against a docs/roofline.md
anchor figure (1.0 = the published wall holds). Exit code 0 on success, 2 on
bad input or an engine without the observatory.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def _engine_dump(addr: str, last):
    import asyncio

    import grpc

    from surge_tpu.admin.server import AdminClient

    async def fetch():
        async with grpc.aio.insecure_channel(addr) as channel:
            return await AdminClient(channel).replay_ledger_dump(last)

    return asyncio.run(fetch())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", nargs="?",
                    help="saved DumpReplayLedger JSON file")
    ap.add_argument("--engine", metavar="ADDR",
                    help="live DumpReplayLedger over the engine admin RPC")
    ap.add_argument("--last", type=int, default=None,
                    help="newest N ledger events in the pulled dump")
    ap.add_argument("--out", default="roofline.jsonl",
                    help="append-only JSONL trajectory file "
                         "(default: roofline.jsonl)")
    ap.add_argument("--source", default="",
                    help="row source label (defaults to the engine addr or "
                         "dump file name)")
    ap.add_argument("--note", default="", help="free-form row annotation")
    ap.add_argument("--compare", metavar="ANCHOR",
                    help="print measured/published ratios against a "
                         "docs/roofline.md anchor (e.g. steady-ragged-cpu)")
    args = ap.parse_args(argv)

    if bool(args.dump) == bool(args.engine):
        print("exactly one of a dump file or --engine is required",
              file=sys.stderr)
        return 2

    from surge_tpu.observability.roofline import (REFERENCE, RooflineRecorder,
                                                  against_reference)

    if args.engine:
        try:
            payload = _engine_dump(args.engine, args.last)
        except Exception as exc:  # noqa: BLE001 — a down engine is the finding
            print(f"engine {args.engine}: {exc}", file=sys.stderr)
            return 2
        source = args.source or args.engine
    else:
        try:
            with open(args.dump) as f:
                payload = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"cannot read dump {args.dump}: {exc}", file=sys.stderr)
            return 2
        source = args.source or os.path.basename(args.dump)

    summary = payload.get("summary")
    if not isinstance(summary, dict):
        print("dump carries no ledger summary (not a DumpReplayLedger "
              "envelope?)", file=sys.stderr)
        return 2

    row = RooflineRecorder(args.out).record(summary, source=source,
                                            note=args.note)
    print(json.dumps(row))
    if args.compare:
        if args.compare not in REFERENCE:
            print(f"unknown anchor {args.compare!r} "
                  f"(have: {', '.join(sorted(REFERENCE))})", file=sys.stderr)
            return 2
        print(json.dumps({"anchor": args.compare,
                          "ratios": against_reference(row, args.compare)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
