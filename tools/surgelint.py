#!/usr/bin/env python
"""surgelint CLI — run the repo-native static analysis suite.

    python tools/surgelint.py                       # canonical surface
    python tools/surgelint.py surge_tpu/log         # one subtree
    python tools/surgelint.py --changed             # only git-dirty files
    python tools/surgelint.py --format=json         # machine consumption
    python tools/surgelint.py --select await-under-lock,orphan-task
    python tools/surgelint.py --write-baseline      # accept current findings
    python tools/surgelint.py --list-rules

Exit 0 = no unbaselined, unsuppressed findings. The rule catalog (what each
rule catches, the historical bug it encodes, how to suppress) lives in
docs/static-analysis.md. Cross-file rules (config-key-registry,
metric-catalog, proto-drift) always aggregate over the full canonical surface
even under --changed/path filters, so a filtered run cannot miss a drift.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from surge_tpu.analysis import (  # noqa: E402
    DEFAULT_TARGETS,
    all_rules,
    render_json,
    render_text,
    run_paths,
    write_baseline,
)

BASELINE_PATH = os.path.join(REPO, ".surgelint-baseline.json")


#: non-module artifacts the repo-scope rules read: a dirty one must trigger
#: a run even when no .py file changed (the drift may live in the artifact)
ARTIFACT_PREFIXES = ("proto/", "docs/", "tests/golden/")


def changed_paths() -> tuple:
    """(changed .py files under the canonical targets, whether a repo-rule
    artifact is dirty) — the fast local loop before a full run."""
    out = subprocess.run(
        ["git", "status", "--porcelain", "-uall"], cwd=REPO,
        capture_output=True, text=True, check=True).stdout
    paths = set()
    for line in out.splitlines():
        paths.add(line[3:].split(" -> ")[-1].strip())
    diff = subprocess.run(
        ["git", "diff", "--name-only", "HEAD"], cwd=REPO,
        capture_output=True, text=True, check=True).stdout
    paths.update(diff.splitlines())
    targets = []
    artifacts_dirty = False
    for p in sorted(paths):
        if not os.path.exists(os.path.join(REPO, p)):
            continue  # deleted file
        if p.startswith(ARTIFACT_PREFIXES):
            artifacts_dirty = True
        if p.endswith(".py") and any(
                p == t or p.startswith(t.rstrip("/") + "/")
                for t in DEFAULT_TARGETS):
            targets.append(os.path.join(REPO, p))
    return targets, artifacts_dirty


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="surgelint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help=f"files/dirs to lint (default: {' '.join(DEFAULT_TARGETS)})")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--changed", action="store_true",
                        help="lint only git-dirty files (working tree vs "
                             "HEAD; committed changes need a full run)")
    parser.add_argument("--select", default="",
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--baseline", default=BASELINE_PATH,
                        help="baseline file (default: .surgelint-baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report baselined findings too")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept all current findings into the baseline")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="show suppressed findings with justifications")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            scope = "repo " if rule.repo_scope else "file "
            print(f"{rid:32s} [{scope}] {rule.summary}")
        return 0

    if args.write_baseline and (args.paths or args.changed or args.select):
        # a filtered run must never overwrite the FULL baseline with its
        # subset — accepted debt elsewhere would silently vanish
        print("surgelint: --write-baseline always runs the full canonical "
              "surface with every rule; ignoring path/rule filters",
              file=sys.stderr)
        args.paths, args.changed, args.select = [], False, ""

    if args.changed:
        paths, artifacts_dirty = changed_paths()
        if not paths and not artifacts_dirty:
            print("surgelint: no changed files under the canonical targets")
            return 0
        # dirty proto/docs/golden with no .py change: still run (paths may be
        # empty — repo-scope rules aggregate over the canonical surface)
    else:
        paths = args.paths or list(DEFAULT_TARGETS)

    select = [r.strip() for r in args.select.split(",") if r.strip()] or None
    baseline = None if (args.no_baseline or args.write_baseline) else args.baseline
    t0 = time.perf_counter()
    try:
        report = run_paths(paths, REPO, select=select, baseline_path=baseline)
    except (ValueError, FileNotFoundError) as exc:
        print(f"surgelint: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        accepted = [f for f in report.findings
                    if f.rule != "pragma-justification"]  # justify or remove
        write_baseline(args.baseline, accepted)
        print(f"surgelint: wrote {len(accepted)} finding(s) to "
              f"{os.path.relpath(args.baseline, REPO)}")
        return 0

    if args.format == "json":
        print(render_json(report))
    else:
        print(render_text(report, verbose=args.verbose))
        print(f"({time.perf_counter() - t0:.2f}s)")
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
