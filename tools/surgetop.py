#!/usr/bin/env python
"""surgetop — the fleet's live console, driven off ONE federated scrape.

A `top`-style, curses-free view of every engine and broker in the fleet:
per-instance role, liveness, scrape staleness, leader epoch, high-watermark
lag, WAL fsync round time, resident-slab occupancy, live entities and command
rate — plus the SLO burn-rate table (fast/slow window burn per objective,
breaches highlighted). One `FederatedScraper` pass per refresh; nothing here
talks to more than the scrape surfaces::

    python tools/surgetop.py broker@127.0.0.1:16001,broker@127.0.0.1:16002 \
        engine@127.0.0.1:7001
    python tools/surgetop.py broker@127.0.0.1:16001 --interval 5
    python tools/surgetop.py broker@127.0.0.1:16001 --once --format=json

Targets are ``role@address`` specs (comma- or space-separated):
``broker@host:port`` scrapes the log-service `GetMetricsText` RPC,
``engine@host:port`` the admin-service one, ``role@http://...`` any plain
exposition endpoint. ``--once --format=json`` emits one machine-readable
snapshot (scripting + the tier-1 smoke); without ``--once`` the console
redraws every ``--interval`` seconds until interrupted.

SLO evaluation uses the shipped ``DEFAULT_SLOS`` (docs/observability.md);
window/threshold knobs come from ``surge.slo.*`` config (env-overridable:
``SURGE_SLO_FAST_WINDOW_MS`` etc.). ``--no-slo`` turns the table off.

Exit code 0 on success (even with targets down — that is a finding, not a
failure), 2 on bad arguments.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # trace_anatomy

#: (header, merged-family, format) — the per-instance columns; families
#: absent for a role (no slab on a broker) render as "-"
_COLUMNS = (
    ("epoch", "surge_log_replication_epoch", "{:.0f}"),
    ("leader", "surge_log_broker_is_leader", "{:.0f}"),
    ("p-led", "surge_cluster_partitions_led", "{:.0f}"),
    ("m-epoch", "surge_cluster_member_epoch", "{:.0f}"),
    ("native", "surge_log_native_active", "{:.0f}"),
    ("hwm-lag", "surge_log_hwm_lag_records", "{:.0f}"),
    ("fsync-ms", "surge_log_journal_fsync_round_timer", "{:.2f}"),
    ("slab", "surge_replay_resident_slab_occupancy", "{:.0f}"),
    ("waste", "surge_replay_resident_padding_waste_ratio", "{:.1f}"),
    ("ev/us", "surge_replay_resident_events_per_dispatch_us", "{:.2f}"),
    ("skew", "surge_replay_resident_shard_skew", "{:.2f}"),
    # bucketed ragged dispatch: bucket programs + lane-slot fill per round
    ("bkts", "surge_replay_resident_bucket_dispatches", "{:.0f}"),
    ("fill", "surge_replay_resident_bucket_fill_ratio", "{:.2f}"),
    # materialized views: live changefeed subscriptions across views
    ("v-subs", "surge_replay_views_subscribers", "{:.0f}"),
    ("entities", "surge_engine_live_entities", "{:.0f}"),
    ("cmd/s", "surge_engine_command_rate_one_minute_rate", "{:.1f}"),
    # saga plane: in-flight saga drivers on the manager's engine
    ("sagas", "surge_saga_active", "{:.0f}"),
    # consistency observatory: open divergences (anything > 0 is a page)
    ("audit", "surge_audit_unresolved_divergences", "{:.0f}"),
)


#: per-instance (kept-counter, dominant-leg) memo: a standing console must
#: not open a fresh channel + DumpTraces RPC per target per frame when the
#: target kept nothing new since the last frame (or nothing at all, ever)
_DOM_LEG_CACHE = {}


def _dominant_for_target(target, kept, last=64):
    """The dominant critical-path leg of one target's tail-kept traces
    (its DumpTraces RPC attributed in isolation) — the `dom-leg` column.
    ``kept`` is the target's scraped ``surge_trace_kept`` counter: the RPC
    only fires when it MOVED since the cached frame (None/0 = untraced or
    nothing kept — no RPC at all). Returns None (rendered "-") for
    HTTP-only targets, untraced processes, or any fetch failure: the column
    is evidence when present, never a reason the console fails."""
    addr = target.address
    if not addr or addr.startswith("http") or not kept:
        return None
    cached = _DOM_LEG_CACHE.get(target.instance)
    if cached is not None and cached[0] == kept:
        return cached[1]
    try:
        if target.role == "engine":
            from trace_anatomy import _engine_dump

            dump = _engine_dump(addr, last)
        else:
            from trace_anatomy import _broker_dump

            dump = _broker_dump(addr, last)
        from surge_tpu.observability.anatomy import dominant_leg

        verdict = dominant_leg([dump])
        leg = verdict["dominant"] if verdict else None
        _DOM_LEG_CACHE[target.instance] = (kept, leg)
        return leg
    except Exception:  # noqa: BLE001 — a down/untraced target shows "-"
        return None


def _sample_value(families, name, instance, suffix=""):
    fam = families.get(name)
    if fam is None:
        return None
    for s in fam.samples:
        if s.suffix == suffix and dict(s.labels).get("instance") == instance:
            return s.value
    return None


def fleet_rows(scraper, families=None, anatomy=True):
    """One dict per target from the merged families: the console table's
    data, importable for tests and scripting. ``anatomy`` adds the
    ``dom-leg`` column — each target's dominant critical-path leg from its
    tail-kept traces (DumpTraces RPC); "-" for HTTP/untraced/down targets."""
    if families is None:
        families = {f.name: f for f in scraper.last_merged()}
    rows = []
    for t in scraper.targets:
        row = {"instance": t.instance, "role": t.role,
               "up": bool(_sample_value(families, "up", t.instance)),
               "staleness_s": _sample_value(
                   families, "surge_fleet_scrape_staleness_seconds",
                   t.instance)}
        for header, family, _fmt in _COLUMNS:
            row[header] = _sample_value(families, family, t.instance)
        kept = _sample_value(families, "surge_trace_kept", t.instance,
                             suffix="_total")
        row["dom-leg"] = (_dominant_for_target(t, kept)
                          if anatomy else None)
        rows.append(row)
    return rows


def _fmt(value, fmt="{}"):
    if value is None:
        return "-"
    try:
        return fmt.format(value)
    except (ValueError, TypeError):
        return str(value)


def render_table(rows, slo_status, summary) -> str:
    """The console frame as one string (testable without a TTY)."""
    headers = (["instance", "role", "up", "stale-s"]
               + [h for h, _f, _m in _COLUMNS] + ["dom-leg"])
    table = []
    for row in rows:
        table.append([
            row["instance"], row["role"], "1" if row["up"] else "0",
            _fmt(row["staleness_s"], "{:.1f}"),
        ] + [_fmt(row[h], m) for h, _f, m in _COLUMNS]
          + [_fmt(row.get("dom-leg"))])
    widths = [max(len(h), *(len(r[i]) for r in table)) if table else len(h)
              for i, h in enumerate(headers)]
    max_burn = max((s["burn_fast"] for s in slo_status), default=0.0)
    breached = [s["objective"] for s in slo_status if s["breached"]]
    lines = [f"surgetop — {summary['up']}/{summary['targets']} up"
             + (f", max SLO burn {max_burn:.2f}" if slo_status else "")
             + (f", BREACHED: {','.join(breached)}" if breached else "")
             + (f", scrape errors: {sorted(summary['errors'])}"
                if summary["errors"] else "")]
    lines.append("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
    for r in table:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    if slo_status:
        lines.append("")
        lines.append("objective            target   burn-fast  burn-slow  "
                     "state")
        for s in slo_status:
            lines.append(f"{s['objective']:<20s} {s['target']:<8g} "
                         f"{s['burn_fast']:<10.2f} {s['burn_slow']:<10.2f} "
                         f"{'BREACH' if s['breached'] else 'ok'}")
    return "\n".join(lines)


def snapshot(scraper, anatomy=True) -> dict:
    """One federation pass → the machine-readable console state."""
    summary = scraper.scrape_once()
    rows = fleet_rows(scraper, anatomy=anatomy)
    slo_status = scraper.slo.status() if scraper.slo is not None else []
    return {"summary": summary, "instances": rows, "slo": slo_status,
            "breached": (scraper.slo.breached()
                         if scraper.slo is not None else [])}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("targets", nargs="+",
                    help="role@address specs (comma- or space-separated)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds (live mode)")
    ap.add_argument("--once", action="store_true",
                    help="one snapshot, no redraw loop")
    ap.add_argument("--format", choices=["table", "json"], default="table")
    ap.add_argument("--no-slo", action="store_true",
                    help="skip SLO evaluation")
    ap.add_argument("--no-anatomy", action="store_true",
                    help="skip the dom-leg column (no DumpTraces RPCs)")
    args = ap.parse_args(argv)

    from surge_tpu.observability import (DEFAULT_SLOS, FederatedScraper,
                                         SLOEngine)

    specs = [s for arg in args.targets for s in arg.split(",") if s.strip()]
    if not specs:
        print("no targets", file=sys.stderr)
        return 2
    scraper = FederatedScraper(specs)
    if not args.no_slo:
        scraper.slo = SLOEngine(DEFAULT_SLOS, metrics=scraper.metrics,
                                flight=None)
    try:
        while True:
            snap = snapshot(scraper, anatomy=not args.no_anatomy)
            if args.format == "json":
                print(json.dumps(snap, indent=None if args.once else 2))
            else:
                frame = render_table(snap["instances"], snap["slo"],
                                     snap["summary"])
                if not args.once:
                    # ANSI clear + home: the curses-free redraw
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(frame)
                sys.stdout.flush()
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        scraper.stop()


if __name__ == "__main__":
    sys.exit(main())
