#!/usr/bin/env python
"""Background TPU claim/sweep retry loop.

Each axon claim pends up to ~25 minutes before the pool answers; four rounds
of single-shot attempts produced zero on-chip artifacts.  This loop keeps one
claim outstanding at a time for the whole session: run `onchip_sweep` as a
subprocess (fresh process per attempt — a failed backend poisons the jax
runtime it initialized in), check whether `BENCH_ONCHIP.json` banked real
silicon numbers, and stop the moment it did.

Stand this down (kill the process) before the driver's own bench run so two
claims never race on the tunnel.  Writes a heartbeat log to its stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "BENCH_ONCHIP.json")
FULL = os.environ.get("SURGE_ONCHIP_FULL", "/tmp/corpus_full100m")
DEADLINE_UTC = os.environ.get("SURGE_RETRY_DEADLINE", "")  # "HH:MM" today, UTC


def _deadline_epoch() -> float:
    """Resolve HH:MM (UTC, today — or tomorrow if already past) to an epoch
    once at startup, so an attempt that pends across midnight still stops."""
    if not DEADLINE_UTC:
        return float("inf")
    try:
        hh, mm = (int(x) for x in DEADLINE_UTC.split(":"))
    except ValueError:
        return float("inf")
    now = time.time()
    g = time.gmtime(now)
    import calendar

    target = calendar.timegm((g.tm_year, g.tm_mon, g.tm_mday, hh, mm, 0, 0, 0, 0))
    return target if target > now else target + 86400.0


DEADLINE_EPOCH = _deadline_epoch()


def banked() -> bool:
    """True only when the artifact holds at least one real on-chip measurement
    (every smoke row can be an {"error": ...} dict — those don't count)."""
    try:
        with open(ARTIFACT) as f:
            art = json.load(f)
    except (OSError, ValueError):
        return False
    if art.get("platform") in (None, "cpu"):
        return False
    return any(c.get("verified") and "events_per_sec" in c
               for c in art.get("smoke", {}).get("configs", []))


def artifact_platform() -> str | None:
    """The platform the LAST sweep attempt actually initialized, or None."""
    try:
        with open(ARTIFACT) as f:
            return json.load(f).get("platform")
    except (OSError, ValueError):
        return None


def main() -> None:
    attempt = 0
    while not banked():
        if time.time() >= DEADLINE_EPOCH:
            print(f"[{time.strftime('%H:%M:%S')}] deadline {DEADLINE_UTC}Z "
                  "reached; standing down", flush=True)
            return
        attempt += 1
        # trust the corpus only once its last-written marker exists — a dir
        # alone may be a partial build (prebuild killed mid-synth)
        full = FULL if os.path.exists(os.path.join(FULL, "complete.json")) else ""
        cmd = [sys.executable, os.path.join(REPO, "onchip_sweep.py")]
        if full:
            cmd.append(full)
        print(f"[{time.strftime('%H:%M:%S')}] attempt {attempt}: {cmd}",
              flush=True)
        t0 = time.perf_counter()
        wall_t0 = time.time()
        proc = subprocess.run(cmd, cwd=REPO)
        dt = time.perf_counter() - t0
        print(f"[{time.strftime('%H:%M:%S')}] attempt {attempt} exited "
              f"rc={proc.returncode} after {dt:.0f}s", flush=True)
        if banked():
            break
        try:  # only trust the platform field THIS attempt wrote — a stale
            # cpu artifact from an earlier session must not stand the loop
            # down when the current attempt crashed before writing anything
            fresh = os.path.getmtime(ARTIFACT) >= wall_t0 - 1
        except OSError:
            fresh = False
        if fresh and artifact_platform() == "cpu":
            # the sweep came up on the host CPU backend (tunnel env absent or
            # jax fell back): every retry would re-run the FULL sweep — the
            # ~5-minute _verify_families pass included — and bank nothing,
            # hammering until the deadline. That is a hard refuse: stand down
            # and let the operator fix the tunnel env first (ADVICE r5).
            print(f"[{time.strftime('%H:%M:%S')}] attempt {attempt} "
                  "initialized the CPU backend, not a TPU — the tunnel env is "
                  "absent/broken and retrying cannot bank on-chip numbers; "
                  "standing down", flush=True)
            return
        # pool answered fast (hard refuse) -> don't hammer; pool pended the
        # full ~25 min -> re-queue immediately, the wait IS the backoff
        time.sleep(120 if dt < 300 else 10)
    if banked():
        print(f"[{time.strftime('%H:%M:%S')}] on-chip artifact banked; done",
              flush=True)


if __name__ == "__main__":
    main()
