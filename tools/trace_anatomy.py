#!/usr/bin/env python
"""trace_anatomy — assemble tail-kept trace dumps and render the per-leg
critical-path attribution table (the where-does-the-time-go console).

Dumps come from the broker's ``DumpTraces`` log-service RPC, the engine's
admin ``DumpTraces`` RPC, or files saved earlier (each the JSON envelope
:meth:`surge_tpu.tracing.tail.TraceRing.dump` writes)::

    python tools/trace_anatomy.py engine.json broker1.json broker2.json
    python tools/trace_anatomy.py --broker 127.0.0.1:16001 \
        --broker 127.0.0.1:16002 --engine 127.0.0.1:7001
    python tools/trace_anatomy.py --broker 127.0.0.1:16001 --once --format=json

Spans from different processes are placed on one timeline through each
dump's mono↔wall header pair (skew-proof — docs/observability.md), grouped
into whole traces, and decomposed into the named critical-path legs (entity
mailbox wait → publisher linger → lane dispatch → broker gate wait →
journal fsync → replication ack → reply decode → router resolve — plus the
DEVICE legs gather-coalesce → device-dispatch → fetch-barrier → decode off
resident-gather / query-scan / replay-profiler spans). The table
aggregates kept COMMAND traces into per-leg p50/p99/total/share rows and
names the dominant leg; ``--format=json`` emits the machine-readable verdict
(scripting + the tier-1 smoke). ``--once`` is accepted for symmetry with
surgetop (this tool is always one-shot).

Exit code 0 on success (even with zero attributable traces — that is a
finding, not a failure), 2 on bad input.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def _load_file(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _broker_dump(addr: str, last) -> dict:
    from surge_tpu.log.client import GrpcLogTransport

    client = GrpcLogTransport(addr)
    try:
        return client.trace_dump(last)
    finally:
        client.close()


def _engine_dump(addr: str, last) -> dict:
    import asyncio

    import grpc

    from surge_tpu.admin.server import AdminClient

    async def fetch():
        async with grpc.aio.insecure_channel(addr) as channel:
            return await AdminClient(channel).trace_dump(last)

    return asyncio.run(fetch())


def render_table(table: dict) -> str:
    """The attribution table as one string (testable without a TTY)."""
    lines = [f"command anatomy — {table['traces']} trace(s)"
             + (f", dominant leg: {table['dominant']} "
                f"({table['dominant_share'] * 100:.1f}% of critical path)"
                if table["dominant"] else "")]
    lines.append(f"{'leg':<18s} {'p50 ms':>10s} {'p99 ms':>10s} "
                 f"{'total ms':>11s} {'share':>7s}")
    for leg, row in table["legs"].items():
        lines.append(f"{leg:<18s} {row['p50']:>10.3f} {row['p99']:>10.3f} "
                     f"{row['total_ms']:>11.3f} {row['share'] * 100:>6.1f}%")
    if table["slowest"]:
        lines.append("")
        lines.append("slowest kept traces:")
        for r in table["slowest"]:
            lines.append(f"  {r['trace_id'][:16]:<17s} "
                         f"{r['duration_ms']:>10.3f}ms  "
                         f"dominant: {r['dominant']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dumps", nargs="*", help="saved trace-dump JSON files")
    ap.add_argument("--broker", action="append", default=[], metavar="ADDR",
                    help="live DumpTraces over the log-service RPC "
                         "(repeatable)")
    ap.add_argument("--engine", action="append", default=[], metavar="ADDR",
                    help="live DumpTraces over the engine admin RPC "
                         "(repeatable)")
    ap.add_argument("--last", type=int, default=None,
                    help="newest N kept traces per source")
    ap.add_argument("--once", action="store_true",
                    help="accepted for CLI symmetry (always one-shot)")
    ap.add_argument("--format", choices=["table", "json"], default="table")
    ap.add_argument("--all-traces", action="store_true",
                    help="attribute every kept trace, not just "
                         "command-shaped ones")
    args = ap.parse_args(argv)

    if not args.dumps and not args.broker and not args.engine:
        print("no dump files or --broker/--engine targets", file=sys.stderr)
        return 2

    from surge_tpu.observability.anatomy import (assemble_traces,
                                                 attribution_table)

    dumps = []
    try:
        for path in args.dumps:
            dumps.append(_load_file(path))
    except (OSError, ValueError) as exc:
        print(f"cannot read dump {path}: {exc}", file=sys.stderr)
        return 2
    errors = []
    for addr in args.broker:
        try:
            dumps.append(_broker_dump(addr, args.last))
        except Exception as exc:  # noqa: BLE001 — a down broker is a finding
            errors.append(f"broker {addr}: {exc}")
    for addr in args.engine:
        try:
            dumps.append(_engine_dump(addr, args.last))
        except Exception as exc:  # noqa: BLE001 — a down engine is a finding
            errors.append(f"engine {addr}: {exc}")

    traces = assemble_traces(dumps)
    table = attribution_table(traces, command_only=not args.all_traces)
    if args.format == "json":
        print(json.dumps({**table, "sources": len(dumps),
                          "errors": errors}))
    else:
        for err in errors:
            print(f"WARN: {err}", file=sys.stderr)
        print(render_table(table))
    return 0


if __name__ == "__main__":
    sys.exit(main())
